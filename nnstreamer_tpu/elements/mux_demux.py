"""tensor_mux / tensor_demux — frame composition and decomposition.

References: gst/nnstreamer/elements/gsttensormux.c (CollectPads + time-sync
:120,204-211; sync-mode/sync-option props) and gsttensordemux.c
(``tensorpick`` selection).

mux: N single-tensor (or multi-tensor) streams → one frame carrying all
tensors, synchronized per SyncPolicy. demux: one multi-tensor frame → N src
pads, optionally picking a subset (``tensorpick="0,2"``; entries may also be
grouped "0:1,2" to emit multi-tensor buffers per pad).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core.buffer import Buffer
from ..core.types import Caps, TensorsConfig, TensorsInfo
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.sync import SyncPolicy
from .collect_base import CollectingElement


@register_element
class TensorMux(CollectingElement):
    ELEMENT_NAME = "tensor_mux"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.sync_mode: str = "slowest"
        self.sync_option: str = ""
        super().__init__(name, **props)
        self.add_src_pad(template=Caps.any_tensors())
        self._pad_caps: Dict[str, Caps] = {}
        self._caps_sent = False

    def start(self) -> None:
        policy = SyncPolicy.parse(self.sync_mode)
        base_key = None
        base_dur = 0
        if policy is SyncPolicy.BASEPAD and self.sync_option:
            parts = str(self.sync_option).split(":")
            base_key = f"sink_{int(parts[0])}"
            if len(parts) > 1:
                base_dur = int(parts[1])
        self._make_collect(policy, base_key=base_key, base_duration_ns=base_dur)
        self._pad_caps.clear()
        self._caps_sent = False

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        with self._lock:
            self._pad_caps[pad.name] = caps
            if not self._caps_sent and len(self._pad_caps) == len(self.sink_pads):
                self._caps_sent = True
                infos = []
                rate = None
                for p in self.sink_pads:
                    cfg = self._pad_caps[p.name].to_config()
                    infos.extend(cfg.info.infos)
                    rate = rate or (cfg.rate if cfg.rate > 0 else None)
                out = TensorsConfig(TensorsInfo(tuple(infos)), rate or 0)
                self._out_config = out
                self.send_caps_all(Caps.tensors(out))

    def _emit(self, sets) -> FlowReturn:
        ret = FlowReturn.OK
        for frame, pts in sets:
            mems: List = []
            meta: dict = {}
            offset = None
            for p in self.sink_pads:
                b = frame[p.name]
                mems.extend(b.memories)
                # union constituent metadata, first pad wins on conflicts
                # (e.g. query_client_id must survive a mux in a server
                # pipeline loop, reference serversink pairing semantics)
                for k, v in b.meta.items():
                    meta.setdefault(k, v)
                if offset is None:
                    offset = b.offset
            out = Buffer(mems, pts=pts, offset=offset, meta=meta,
                         config=getattr(self, "_out_config", None))
            r = self.push(out)
            if r is FlowReturn.ERROR:
                ret = r
        return ret


@register_element
class TensorDemux(Element):
    ELEMENT_NAME = "tensor_demux"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.tensorpick: Optional[str] = None
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self._groups: Optional[List[List[int]]] = None

    def _parse_pick(self, num_tensors: int) -> List[List[int]]:
        if not self.tensorpick:
            return [[i] for i in range(num_tensors)]
        groups = []
        for part in str(self.tensorpick).split(","):
            part = part.strip()
            idxs = [int(x) for x in part.split(":")] if part else []
            groups.append(idxs)
        return groups

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        cfg = caps.to_config()
        self._groups = self._parse_pick(cfg.info.num_tensors)
        if len(self.src_pads) != len(self._groups):
            raise ValueError(
                f"tensor_demux: {len(self._groups)} outputs configured but "
                f"{len(self.src_pads)} pads linked")
        for i, grp in enumerate(self._groups):
            infos = tuple(cfg.info[j] for j in grp)
            out = TensorsConfig(TensorsInfo(infos), cfg.rate)
            self.send_caps(Caps.tensors(out), i)

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        ret = FlowReturn.OK
        for i, grp in enumerate(self._groups):
            mems = [buf.memories[j] for j in grp]
            r = self.push(buf.with_memories(mems), i)
            if r is FlowReturn.ERROR:
                ret = r
        return ret
