"""tensor_filter — THE core element: wraps any NN backend as a stream filter.

Reference: gst/nnstreamer/tensor_filter/tensor_filter.c (+ _common.c).
Responsibilities mirrored here:
  * framework resolution incl. ``framework=auto`` detection from the model
    (tensor_filter_common.c:1153-1416) and lazy backend open
    (gst_tensor_filter_common_open_fw, :2394-2429);
  * caps negotiation driven by model I/O metadata (transform_caps/set_caps,
    tensor_filter.c:113-123 — model info decides stream types);
  * input-combination / output-combination tensor picking
    (tensor_filter.c:607-646, 709-766);
  * invoke with rolling latency/throughput statistics
    (tensor_filter.c:321-420; props latency/throughput);
  * QoS throttling driven by tensor_rate's upstream QOS events
    (tensor_filter.c:425-480,526);
  * model hot-reload via RELOAD_MODEL event / ``update_model()``
    (is-updatable, evt_update_model tensor_filter.c:76);
  * shared backend instances via ``shared-tensor-filter-key``
    (tensor_filter_common.c:570-602);
  * invoke soft-failure = drop buffer (tensor_filter.c:702-705).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

from ..core.buffer import Buffer, TensorMemory
from ..core.hw import AcceleratorSpec
from ..core.log import logger
from ..core.types import Caps, TensorFormat, TensorsConfig, TensorsInfo
from ..filters.base import (
    FilterFramework,
    FilterProps,
    InvokeStats,
    detect_framework,
    find_filter,
    shared_model_get_or_create,
    shared_model_release,
)
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.events import Event, EventType
from ..obs import quality as _quality
from ..resilience.policy import deadline_of

log = logger("tensor_filter")


@register_element
class TensorFilter(Element):
    ELEMENT_NAME = "tensor_filter"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.framework = "auto"
        self.model: Any = None
        self.custom = ""
        self.accelerator = ""
        self.is_updatable = False
        self.input: Optional[str] = None        # dims override, e.g. "3:224:224:1"
        self.inputtype: Optional[str] = None
        self.inputname: Optional[str] = None    # graph op names (tensorflow)
        self.output: Optional[str] = None
        self.outputtype: Optional[str] = None
        self.outputname: Optional[str] = None
        # data layouts, comma-separated per tensor: none/any/NHWC/NCHW
        # (tensor_filter_common.c:913-940). NCHW on the XLA backend fuses
        # the channel-first<->channel-last transpose into the XLA program.
        self.inputlayout: Optional[str] = None
        self.outputlayout: Optional[str] = None
        self.input_combination: Optional[str] = None   # e.g. "0,2"
        self.output_combination: Optional[str] = None  # e.g. "i0,o0"
        self.shared_tensor_filter_key: Optional[str] = None
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self.add_src_pad(template=Caps.any_tensors())
        self.fw: Optional[FilterFramework] = None
        self.stats = InvokeStats()
        self._shared_key_used: Optional[str] = None
        self._throttle_interval_ns = 0
        self._last_pushed_pts: Optional[int] = None
        self._out_config: Optional[TensorsConfig] = None
        self._in_pick: Optional[List[int]] = None
        self._out_spec: Optional[List[tuple]] = None
        self._parse_combinations()

    # -- properties ---------------------------------------------------------- #
    @property
    def latency(self) -> int:
        """Average invoke latency µs over last 10 invokes (reference prop)."""
        return self.stats.latency_us

    @property
    def throughput(self) -> int:
        """FPS×1000 since first invoke (reference prop)."""
        return self.stats.throughput

    @property
    def inputranks(self) -> str:
        """Comma-separated ranks of the model's input tensors (readable
        prop, PROP_INPUTRANKS)."""
        return self._ranks_of(0)

    @property
    def outputranks(self) -> str:
        """Comma-separated ranks of the model's output tensors (readable
        prop, PROP_OUTPUTRANKS)."""
        return self._ranks_of(1)

    def _ranks_of(self, which: int) -> str:
        if self.fw is None:
            return ""
        info = self.fw.get_model_info()[which]
        if info is None:
            return ""
        return ",".join(str(t.rank) for t in info)

    _LAYOUTS = ("", "none", "any", "nhwc", "nchw")

    @classmethod
    def _parse_layout(cls, spec: Optional[str]) -> tuple:
        if not spec:
            return ()
        vals = tuple(p.strip().lower() for p in str(spec).split(","))
        for v in vals:
            if v not in cls._LAYOUTS:
                raise ValueError(
                    f"tensor_filter: unknown layout {v!r} "
                    "(allowed: none/any/NHWC/NCHW)")
        return vals

    def _parse_combinations(self) -> None:
        if self.input_combination:
            self._in_pick = [int(x) for x in str(self.input_combination).split(",")]
        if self.output_combination:
            spec = []
            for part in str(self.output_combination).split(","):
                part = part.strip().lower()
                if part.startswith("i"):
                    spec.append(("i", int(part[1:])))
                elif part.startswith("o"):
                    spec.append(("o", int(part[1:])))
                else:
                    raise ValueError(
                        f"output-combination entries must be iN/oN: {part!r}")
            self._out_spec = spec

    # -- lifecycle ----------------------------------------------------------- #
    def _open_fw(self) -> None:
        if self.fw is not None:
            return
        fw_name = self.framework
        if fw_name in ("auto", "", None):
            fw_name = detect_framework(self.model)
            if fw_name is None:
                raise ValueError(
                    f"tensor_filter {self.name}: cannot auto-detect framework "
                    f"for model {self.model!r}")
        cls = find_filter(fw_name)
        if cls is None:
            raise ValueError(f"tensor_filter: unknown framework {fw_name!r}")
        in_layout = self._parse_layout(self.inputlayout)
        out_layout = self._parse_layout(self.outputlayout)
        if "nchw" in in_layout + out_layout and not cls.SUPPORTS_LAYOUT:
            # a backend that ignores the declared layout would run
            # unpermuted data and return silently wrong results
            raise ValueError(
                f"tensor_filter {self.name}: framework {fw_name!r} does "
                "not implement NCHW layout conversion (the xla-tpu "
                "backend does; torch models are NCHW-native already)")
        props = FilterProps(
            model=self.model,
            custom=self.custom,
            accelerator=AcceleratorSpec.parse(self.accelerator),
            input_info=self._override_info(self.input, self.inputtype, self.inputname),
            output_info=self._override_info(self.output, self.outputtype, self.outputname),
            is_updatable=self.is_updatable,
            input_layout=in_layout,
            output_layout=out_layout,
        )
        if self.shared_tensor_filter_key:
            key = self.shared_tensor_filter_key
            self._shared_key_used = key

            def factory() -> FilterFramework:
                fw = cls()
                fw.open(props)
                return fw

            self.fw = shared_model_get_or_create(key, factory)
        else:
            fw = cls()
            fw.open(props)  # only adopt a successfully opened backend
            self.fw = fw
        self.resolved_framework = fw_name

    @staticmethod
    def _override_info(dims: Optional[str], types: Optional[str],
                       names: Optional[str] = None) -> Optional[TensorsInfo]:
        if dims and types:
            return TensorsInfo.from_strings(dims, types, names)
        return None

    def start(self) -> None:
        self._open_fw()
        self._last_pushed_pts = None

    def sched_enroll(self, engine: Any, tenant: Any) -> None:
        """Route this filter's invokes through a sched.DeviceEngine:
        same-model/same-shape work from OTHER tenants coalesces with
        ours into one device batch. Installed by
        ``DeviceEngine.attach_pipeline``; ``sched_detach`` (base class)
        restores direct dispatch. Zero cost when never called — chain()
        pays one attribute None check either way."""
        self._open_fw()
        self._sched_exec = engine.executor(tenant, self.fw,
                                           label=self.name)

    def stop(self) -> None:
        self._sched_exec = None  # closing fw invalidates the executor
        if self.fw is not None:
            if self._shared_key_used:
                if shared_model_release(self._shared_key_used):
                    self.fw.close()
            else:
                self.fw.close()
            self.fw = None

    # -- negotiation ---------------------------------------------------------- #
    def on_caps(self, pad: Pad, caps: Caps) -> None:
        if caps.media_type != "other/tensors":
            raise ValueError(
                f"tensor_filter accepts other/tensors, got {caps.media_type} "
                "(insert tensor_converter upstream)")
        self._open_fw()
        in_config = caps.to_config()
        in_info, out_info = self.fw.get_model_info()
        stream_info = in_config.info
        model_sees = self._picked_info(stream_info)
        # with a fused preprocessing stage the wire caps describe the
        # *transformed* stream while raw arrays reach the jit; the fused
        # program itself validates shapes at trace time
        fused = getattr(self.fw, "_fused_pre", None) is not None
        if getattr(self.fw, "flexible_output", False):
            # bucketed dynamic-count invoke: region count varies per frame,
            # so both ends of the element stay flexible-format
            pad.caps = caps
            self._out_config = TensorsConfig(
                TensorsInfo((), TensorFormat.FLEXIBLE), in_config.rate)
            self.send_caps_all(Caps.tensors(self._out_config))
            return
        if in_info is None:
            out_info = self.fw.set_input_info(model_sees)
        elif not fused and stream_info.format is TensorFormat.STATIC and \
                not in_info.is_compatible(model_sees):
            raise ValueError(
                f"tensor_filter {self.name}: stream {model_sees} incompatible "
                f"with model input {in_info}")
        if out_info is None:
            out_info = self.fw.set_input_info(model_sees)
        pad.caps = caps
        final_out = self._combined_out_info(stream_info, out_info)
        self._out_config = TensorsConfig(final_out, in_config.rate)
        self.send_caps_all(Caps.tensors(self._out_config))

    def _picked_info(self, stream_info: TensorsInfo) -> TensorsInfo:
        if self._in_pick is None:
            return stream_info
        return TensorsInfo(tuple(stream_info[i] for i in self._in_pick))

    def _combined_out_info(self, in_info: TensorsInfo, out_info: TensorsInfo) -> TensorsInfo:
        if self._out_spec is None:
            return out_info
        infos = []
        for kind, idx in self._out_spec:
            infos.append(in_info[idx] if kind == "i" else out_info[idx])
        return TensorsInfo(tuple(infos))

    # -- dataflow -------------------------------------------------------------- #
    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        if self.fw is None:
            raise RuntimeError("tensor_filter: backend not opened")
        # QoS throttling (tensor_rate contract)
        if self._throttle_interval_ns > 0 and buf.pts is not None \
                and self._last_pushed_pts is not None \
                and buf.pts < self._last_pushed_pts + self._throttle_interval_ns:
            return FlowReturn.OK  # drop
        inputs = buf.memories
        if self._in_pick is not None:
            model_inputs = [inputs[i] for i in self._in_pick]
        else:
            model_inputs = inputs
        t0 = time.monotonic_ns()
        if self._sched_exec is not None:
            # scheduled path: the engine coalesces this invoke with
            # same-shape work from other tenants; a deadline-shed
            # result comes back as None and rides the soft-drop below
            outputs = self._sched_exec(model_inputs, deadline_of(buf))
        else:
            outputs = self.fw.invoke(model_inputs)
        self.stats.record(time.monotonic_ns() - t0)
        if outputs is None:
            return FlowReturn.OK  # backend soft-drop
        if self._out_spec is not None:
            mems: List[TensorMemory] = []
            for kind, idx in self._out_spec:
                mems.append(inputs[idx] if kind == "i" else outputs[idx])
        else:
            mems = list(outputs)
        out = buf.with_memories(mems, config=self._out_config)
        # data-plane quality tap (obs/quality): the model's raw output
        # buffer; host-only observation, so a device-resident output is
        # counted as skipped rather than copied back
        qhook = _quality.QUALITY_HOOK
        if qhook is not None:
            qhook.observe_filter(self.name, out)
        self._last_pushed_pts = buf.pts
        return self.push(out)

    # -- events ---------------------------------------------------------------- #
    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        if event.type is EventType.QOS:
            self._throttle_interval_ns = int(event.data.get("interval_ns", 0))
            return  # consumed (reference: filter is the throttle point)
        if event.type is EventType.RELOAD_MODEL:
            self.update_model(event.data["model"])
            return
        super().handle_upstream_event(pad, event)

    def update_model(self, model: Any) -> None:
        """Hot model swap without pipeline restart (is-updatable)."""
        if not self.is_updatable:
            raise RuntimeError(f"tensor_filter {self.name}: not is-updatable")
        if self.fw is None:
            self.model = model
            return
        self.fw.reload_model(model)
        self.model = model
