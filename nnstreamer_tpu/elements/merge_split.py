"""tensor_merge / tensor_split — tensor concatenation and slicing.

References: gst/nnstreamer/elements/gsttensormerge.c (mode=linear,
option=first..fourth = concat axis in reference dim order,
gsttensormerge.h:45-58, same sync policies as mux) and gsttensorsplit.c
(``tensorseg`` = per-output slice sizes along an axis).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorInfo, TensorsConfig, TensorsInfo
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.sync import SyncPolicy
from .collect_base import CollectingElement

_AXIS_NAMES = {"first": 0, "second": 1, "third": 2, "fourth": 3}


@register_element
class TensorMerge(CollectingElement):
    """N tensors → one bigger tensor, concatenated along a reference-order
    dim (0=innermost). Device-resident concat via jnp when inputs are on
    device."""

    ELEMENT_NAME = "tensor_merge"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.mode = "linear"
        self.option: str = "third"
        self.sync_mode: str = "slowest"
        self.sync_option: str = ""
        super().__init__(name, **props)
        self.add_src_pad(template=Caps.any_tensors())
        self._pad_caps: Dict[str, Caps] = {}
        self._caps_sent = False
        self._out_config: Optional[TensorsConfig] = None

    @property
    def _nns_axis(self) -> int:
        if self.option in _AXIS_NAMES:
            return _AXIS_NAMES[self.option]
        return int(self.option)

    def start(self) -> None:
        if self.mode != "linear":
            raise ValueError(f"tensor_merge: unsupported mode {self.mode!r}")
        self._make_collect(SyncPolicy.parse(self.sync_mode))
        self._pad_caps.clear()
        self._caps_sent = False

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        with self._lock:
            self._pad_caps[pad.name] = caps
            if self._caps_sent or len(self._pad_caps) < len(self.sink_pads):
                return
            self._caps_sent = True
            infos = [self._pad_caps[p.name].to_config().info[0]
                     for p in self.sink_pads]
            ax = self._nns_axis
            base = infos[0]
            out_dims = list(base.dims)
            while len(out_dims) <= ax:
                out_dims.append(1)
            total = 0
            for inf in infos:
                if inf.dtype is not base.dtype:
                    raise ValueError("tensor_merge: dtype mismatch")
                dims = list(inf.dims) + [1] * (len(out_dims) - inf.rank)
                for d in range(len(out_dims)):
                    if d != ax and dims[d] != out_dims[d]:
                        raise ValueError(
                            f"tensor_merge: dim {d} mismatch {dims} vs {out_dims}")
                total += dims[ax]
            out_dims[ax] = total
            rate = self._pad_caps[self.sink_pads[0].name].to_config().rate
            self._out_config = TensorsConfig(
                TensorsInfo.of(TensorInfo(tuple(out_dims), base.dtype)), rate)
            self.send_caps_all(Caps.tensors(self._out_config))

    def _emit(self, sets) -> FlowReturn:
        import jax.numpy as jnp

        ret = FlowReturn.OK
        for frame, pts in sets:
            arrays = [frame[p.name].memories[0] for p in self.sink_pads]
            rank = max(m.host().ndim if not m.is_device else m.device().ndim
                       for m in arrays)
            np_axis = rank - 1 - self._nns_axis
            if any(m.is_device for m in arrays):
                out = jnp.concatenate([m.device() for m in arrays], axis=np_axis)
            else:
                out = np.concatenate([m.host() for m in arrays], axis=np_axis)
            meta: dict = {}
            for p in self.sink_pads:  # first pad wins on conflicts
                for k, v in frame[p.name].meta.items():
                    meta.setdefault(k, v)
            r = self.push(Buffer([TensorMemory(out)], pts=pts, meta=meta,
                                 config=self._out_config))
            if r is FlowReturn.ERROR:
                ret = r
        return ret


@register_element
class TensorSplit(Element):
    """One tensor → N tensors sliced along a reference dim.

    ``tensorseg`` = comma-separated slice sizes (e.g. "1,2" over axis
    ``option`` default 0=innermost). Reference gsttensorsplit.c semantics.
    """

    ELEMENT_NAME = "tensor_split"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.tensorseg: Optional[str] = None
        self.option: str = "0"  # nns axis to slice
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self._sizes: Optional[List[int]] = None
        self._ref_segs = None  # reference dim-spec grammar (flat regions)

    @property
    def _nns_axis(self) -> int:
        return int(self.option)

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        cfg = caps.to_config()
        info = cfg.info[0]
        if not self.tensorseg:
            raise ValueError("tensor_split requires tensorseg")
        segs = str(self.tensorseg).split(",")
        self._ref_segs = None
        if ":" in segs[0]:
            # reference grammar: each segment is a FULL dims spec
            # ("1:100:100,2:100:100") and the output is a CONTIGUOUS
            # region of the flat raster — offset/size are element counts
            # (gst_tensor_split_get_splited, gsttensorsplit.c:414-445:
            # memcpy from src + sum(prev counts)), NOT a strided slice
            seg_infos = []
            total = 0
            for s in segs:
                dims = [int(d) for d in s.split(":")]
                while len(dims) > 1 and dims[-1] == 1:
                    dims.pop()
                ti = TensorInfo(tuple(dims), info.dtype)
                seg_infos.append(ti)
                total += ti.num_elements
            if total != info.num_elements:
                raise ValueError(
                    f"tensorseg {segs} covers {total} elements, input "
                    f"has {info.num_elements}")
            self._ref_segs = seg_infos
            self._sizes = [t.num_elements for t in seg_infos]
            if len(self.src_pads) != len(seg_infos):
                raise ValueError(
                    f"tensor_split: {len(seg_infos)} segments but "
                    f"{len(self.src_pads)} pads linked")
            for i, ti in enumerate(seg_infos):
                self.send_caps(Caps.tensors(TensorsConfig(
                    TensorsInfo.of(ti), cfg.rate)), i)
            return
        self._sizes = [int(s) for s in segs]
        ax = self._nns_axis
        if sum(self._sizes) != info.dims[ax]:
            raise ValueError(
                f"tensorseg {self._sizes} does not sum to dim {info.dims[ax]}")
        if len(self.src_pads) != len(self._sizes):
            raise ValueError(
                f"tensor_split: {len(self._sizes)} segments but "
                f"{len(self.src_pads)} pads linked")
        for i, s in enumerate(self._sizes):
            dims = list(info.dims)
            dims[ax] = s
            out = TensorsConfig(
                TensorsInfo.of(TensorInfo(tuple(dims), info.dtype)), cfg.rate)
            self.send_caps(Caps.tensors(out), i)

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        m = buf.memories[0]
        arr = m.device() if m.is_device else m.host()
        ret = FlowReturn.OK
        if self._ref_segs is not None:
            # reference semantics: contiguous element ranges of the raster
            flat = arr.reshape(-1)
            off = 0
            for i, ti in enumerate(self._ref_segs):
                n = ti.num_elements
                out = flat[off:off + n].reshape(ti.shape)
                off += n
                r = self.push(
                    buf.with_memories([TensorMemory(out, ti)]), i)
                if r is FlowReturn.ERROR:
                    ret = r
            return ret
        np_axis = arr.ndim - 1 - self._nns_axis
        off = 0
        for i, s in enumerate(self._sizes):
            sl = [slice(None)] * arr.ndim
            sl[np_axis] = slice(off, off + s)
            off += s
            r = self.push(buf.with_memories([TensorMemory(arr[tuple(sl)])]), i)
            if r is FlowReturn.ERROR:
                ret = r
        return ret
