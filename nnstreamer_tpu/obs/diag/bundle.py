"""Debug bundles: one bounded JSON file of *evidence* per incident.

A bundle freezes what the bounded obs rings would otherwise age out —
the slowest span trees (with raw integer-ns spans so the offline
critical-path sweep stays conservation-exact), the event ring, the
profiler's records and samples, sched occupancy/coalesce stats, the
routing view, the fleet action journal, the SLO burn state, and the
data-plane quality stats (per-tap tensor moments + anomaly verdicts,
when obs/quality is on) — plus the build info pinning the code that
produced it.

Collectors are plain callables assembled in :func:`default_collectors`
(lazy imports keep obs package cycles out); a collector that raises
contributes an ``{"error": ...}`` stanza instead of killing the
capture — a diag layer must degrade, never take evidence down with it.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

BUNDLE_VERSION = 1

_ID_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")


def _span_to_doc(span: Any) -> Dict[str, Any]:
    """Raw-span dict: integer monotonic ns endpoints so the offline
    critpath sweep reproduces the online one bit-for-bit."""
    return {
        "trace_id": span.context.trace_id,
        "span_id": span.context.span_id,
        "parent_id": span.context.parent_id,
        "name": span.name,
        "start_ns": span.start_ns,
        "end_ns": span.end_ns,
        "wall": span.wall,
        "attrs": span.attrs,
    }


def default_collectors() -> Dict[str, Callable[[], Any]]:
    """The standard evidence set. Keys become bundle stanzas."""
    from .. import events as _events
    from .. import health as _health
    from .. import profile as _profile
    from .. import slo as _slo
    from .. import tracing as _tracing

    def _sched() -> Any:
        from ... import sched as _sched_pkg

        eng = _sched_pkg.installed()
        if eng is None:
            return None
        return {
            "engine": eng.name,
            "pending": eng.pending(),
            "occupancy": eng.occupancy(),
            "busy_seconds": eng.busy_seconds,
            "wait_seconds": eng.wait_seconds,
            "coalesce": eng.coalesce_stats(),
            "stats": dict(eng.stats),
        }

    def _routing() -> Any:
        from ...query import router as _router

        return _router.routing_view()

    def _fleet_actions() -> Any:
        from ... import fleet as _fleet_pkg

        return _fleet_pkg.snapshot() if _fleet_pkg.enabled() else None

    def _events_snap() -> Any:
        ring = _events.ring()
        return {"dropped": ring.dropped, "events": ring.snapshot()}

    def _profile_snap() -> Any:
        return _profile.profiler().diag_snapshot()

    def _build() -> Any:
        from .. import exporter as _exporter

        return _exporter.build_info()

    def _quality_snap() -> Any:
        # raises when quality is off → degrades to an error stanza,
        # which is the documented "quality was not enabled" marker
        from .. import quality as _quality

        return _quality.bundle_data()

    return {
        "events": _events_snap,
        "profile": _profile_snap,
        "sched": _sched,
        "routing": _routing,
        "fleet_actions": _fleet_actions,
        "slo": _slo.snapshot,
        "health": _health.snapshot,
        "quality": _quality_snap,
        "build": _build,
        "_span_store": _tracing.store,  # consumed structurally below
    }


class BundleStore:
    """Disk-backed bounded bundle set: ``capture`` writes one JSON file
    per incident, oldest bundles are evicted past ``max_bundles``, and
    ``list``/``get``/``refs`` serve the HTTP and push-doc views."""

    def __init__(self, directory: str, *, max_bundles: int = 16,
                 slowest_traces: int = 8,
                 collectors: Optional[Dict[str, Callable[[], Any]]] = None
                 ) -> None:
        self.directory = str(directory)
        self.max_bundles = int(max_bundles)
        self.slowest_traces = int(slowest_traces)
        self._collectors = collectors
        self._lock = threading.Lock()
        self._seq = 0
        self.stats: Dict[str, int] = {"captured": 0, "evicted": 0,
                                      "collector_errors": 0}
        os.makedirs(self.directory, exist_ok=True)

    # -- capture -------------------------------------------------------- #
    def capture(self, cause: Dict[str, Any]) -> Optional[str]:
        """Assemble + persist one bundle; returns its id (None only
        when the write itself failed — collectors degrade per-stanza)."""
        collectors = self._collectors or default_collectors()
        store = None
        doc: Dict[str, Any] = {
            "v": BUNDLE_VERSION,
            "cause": dict(cause),
            "wall": time.time(),
            "mono_ns": time.monotonic_ns(),
            "instance": os.environ.get("NNSTPU_INSTANCE") or None,
        }
        for key, fn in collectors.items():
            if key == "_span_store":
                store = fn()
                continue
            try:
                doc[key] = fn()
            except Exception as e:  # evidence degrades, never raises
                self.stats["collector_errors"] += 1
                doc[key] = {"error": f"{type(e).__name__}: {e}"}
        doc["traces"] = self._collect_traces(store)
        doc["critpath"] = self._collect_critpath(store)

        with self._lock:
            self._seq += 1
            kind = _ID_SAFE.sub("-", str(cause.get("kind", "manual")))
            key = _ID_SAFE.sub("-", str(cause.get("key", "")))[:48]
            bundle_id = f"{int(doc['wall'])}-{self._seq:03d}-{kind}" + (
                f"-{key}" if key else "")
            doc["id"] = bundle_id
            path = os.path.join(self.directory, bundle_id + ".json")
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=str)
                os.replace(tmp, path)
            except OSError:
                return None
            self.stats["captured"] += 1
            self._evict_locked()
        return bundle_id

    def _collect_traces(self, store: Any) -> Optional[Dict[str, Any]]:
        if store is None:
            return None
        try:
            summaries = store.summaries()
            slowest = []
            for summ in summaries[:self.slowest_traces]:
                spans = store.spans_of(summ["trace_id"]) or []
                slowest.append({
                    "trace_id": summ["trace_id"],
                    "root": summ["root"],
                    "duration_ms": summ["duration_ms"],
                    "spans": [_span_to_doc(s) for s in spans
                              if s.end_ns is not None],
                })
            return {"summaries": summaries[:64], "slowest": slowest}
        except Exception as e:
            self.stats["collector_errors"] += 1
            return {"error": f"{type(e).__name__}: {e}"}

    def _collect_critpath(self, store: Any) -> Optional[Dict[str, Any]]:
        if store is None:
            return None
        try:
            from . import critpath as _critpath

            return _critpath.rollup(store)
        except Exception as e:
            self.stats["collector_errors"] += 1
            return {"error": f"{type(e).__name__}: {e}"}

    def _evict_locked(self) -> None:
        paths = self._paths()
        while len(paths) > self.max_bundles:
            victim = paths.pop(0)  # oldest name sorts first (wall.seq)
            try:
                os.remove(victim)
                self.stats["evicted"] += 1
            except OSError:
                break

    # -- queries -------------------------------------------------------- #
    def _paths(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    def list(self) -> List[Dict[str, Any]]:
        """Newest-first light listing for ``GET /debug/bundles``."""
        out = []
        for path in reversed(self._paths()):
            entry: Dict[str, Any] = {
                "id": os.path.basename(path)[:-len(".json")],
                "bytes": 0,
            }
            try:
                entry["bytes"] = os.path.getsize(path)
                with open(path) as f:
                    head = json.load(f)
                entry["cause"] = head.get("cause")
                entry["wall"] = head.get("wall")
                entry["instance"] = head.get("instance")
            except (OSError, ValueError) as e:
                entry["error"] = str(e)
            out.append(entry)
        return out

    def get(self, bundle_id: str) -> Optional[Dict[str, Any]]:
        safe = _ID_SAFE.sub("", str(bundle_id))
        path = os.path.join(self.directory, safe + ".json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def refs(self) -> List[Dict[str, Any]]:
        """Minimal per-bundle references riding fleet push docs, so the
        aggregator can enumerate fleet-wide evidence for an incident."""
        return [{"id": e["id"], "cause": e.get("cause"),
                 "wall": e.get("wall")} for e in self.list()]


def load_bundle(path: str) -> Dict[str, Any]:
    """Offline loader for nns-diag: a bundle file OR a bundle id inside
    a directory."""
    if os.path.isdir(path):
        raise ValueError(f"{path} is a directory; pass the bundle file")
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "v" not in doc:
        raise ValueError(f"{path} is not a debug bundle")
    return doc
