"""obs.diag — critical-path latency attribution + automatic incident
debug bundles.

Three pieces behind one None-gated hook:

- :mod:`.critpath` attributes a request's wall-clock latency exactly
  to segments (admission wait, sched queue wait, device compute, wire,
  KV transfer, migration, re-prefill) over its cross-host span tree,
  with a conservation contract: segments sum to the request's measured
  latency to the nanosecond. ``GET /debug/diag/critpath`` serves the
  per-tenant rollup.
- :mod:`.triggers` + :mod:`.bundle` capture a bounded evidence bundle
  to disk when an SLO burn alert, watchdog DEGRADED, fleet
  scale/migrate action, or cost-model anomaly fires — rate-limited
  and deduped by cause. ``GET /debug/bundles[/<id>]`` serves them and
  fleet push docs reference them.
- :mod:`.cli` (``nns-diag``) loads a bundle offline, prints the
  critical-path waterfall, and emits a Perfetto trace of just the
  implicated requests.

Hook contract (the repo-wide pattern): :data:`DIAG_HOOK` is a module
global, None until :func:`enable` installs a :class:`DiagEngine`.
Every hot-path tap is one attribute load + one None check when off —
pinned by the zero-overhead test. ONLY this package assigns it
(``naming/diag`` lint). ``NNSTPU_DIAG=1`` (or ``=<bundle dir>``)
enables at import; ``nns-launch --diag[=dir]`` from the CLI.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import tracing as _tracing
from .bundle import BundleStore, load_bundle
from .critpath import SEGMENTS, analyze, rollup, segment_of, waterfall
from .triggers import TriggerEngine

__all__ = ["DIAG_HOOK", "DiagEngine", "BundleStore", "TriggerEngine",
           "SEGMENTS", "analyze", "rollup", "segment_of", "waterfall",
           "load_bundle", "enable", "disable", "enabled", "engine",
           "snapshot", "DEFAULT_BUNDLE_DIR"]

DEFAULT_BUNDLE_DIR = ".nnstpu-diag"

#: THE diag hook: None (off, hot paths pay one attribute load + None
#: check) or the enabled DiagEngine. Assigned only here.
DIAG_HOOK: Optional["DiagEngine"] = None


class DiagEngine:
    """The :data:`DIAG_HOOK` target: hot-path taps feed the span store
    and the cost-anomaly detector; cold-path taps (burn alert,
    degrade, fleet action) feed the trigger engine, which captures
    bundles through the store."""

    def __init__(self, bundles: BundleStore, *,
                 min_interval_s: float = 30.0,
                 dedup_window_s: float = 300.0,
                 z_threshold: float = 4.0, min_samples: int = 16,
                 cost_model: Any = None, device_kind: str = "",
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.bundles = bundles
        self.triggers = TriggerEngine(
            bundles.capture, min_interval_s=min_interval_s,
            dedup_window_s=dedup_window_s, z_threshold=z_threshold,
            min_samples=min_samples, clock=clock)
        self.cost_model = cost_model
        self.device_kind = str(device_kind)
        self._lock = threading.Lock()
        #: bounded recent request observations (lm_engine retire tap):
        #: the critpath endpoint's "which requests" evidence
        self._requests: "collections.deque" = collections.deque(maxlen=512)

    # -- hot-path taps (called behind the None gate) -------------------- #
    def tap_submit(self) -> Optional[Any]:
        """sched _submit: capture the submitting thread's trace context
        + a monotonic enqueue stamp so the batch tap can write exact
        diag.sched_wait / diag.sched_run spans into the request's
        trace. None when the submit isn't running under a trace."""
        ctx = _tracing.current_context()
        if ctx is None:
            return None
        return (ctx, time.monotonic_ns())

    def observe_sched_batch(self, engine: str, batch: List[Any],
                            t0_ns: int, t1_ns: int) -> None:
        """sched _execute: synthesize attribution spans for every work
        item that carried a trace context, and feed the batch's
        measured dispatch time to the cost-anomaly detector."""
        store = _tracing.store()
        width = len(batch)
        for w in batch:
            tap = getattr(w, "diag", None)
            if tap is None:
                continue
            ctx, enq_ns = tap
            if enq_ns < t0_ns:
                store.add_span(
                    "diag.sched_wait", ctx.trace_id, ctx.span_id,
                    enq_ns, t0_ns,
                    attrs={"engine": engine, "tenant": w.tenant.name,
                           "label": w.label})
            store.add_span(
                "diag.sched_run", ctx.trace_id, ctx.span_id,
                t0_ns, t1_ns,
                attrs={"engine": engine, "tenant": w.tenant.name,
                       "label": w.label, "width": width})
        head = batch[0]
        label = f"{engine}.{head.label or 'batch'}"
        measured_us = (t1_ns - t0_ns) / 1e3
        expected_us = None
        model = self.cost_model
        if model is not None:
            flops = getattr(head.filt, "flops", None)
            nbytes = getattr(head.filt, "nbytes", None)
            if flops is not None and nbytes is not None:
                expected_us = model.predict(
                    self.device_kind, label, float(flops), float(nbytes))
        self.triggers.observe_cost(label, measured_us, expected_us)

    def observe_request(self, engine: str, rid: int,
                        tenant: Optional[str], trace_id: Optional[str],
                        latency_s: float, shed: bool = False) -> None:
        """serving retire: one finished request's identity + measured
        latency — the join between 'tenant X is slow' and the trace the
        critpath sweep explains."""
        with self._lock:
            self._requests.append({
                "engine": engine, "rid": rid, "tenant": tenant or "-",
                "trace_id": trace_id, "latency_ms": latency_s * 1e3,
                "shed": bool(shed), "wall": time.time()})

    # -- cold-path triggers --------------------------------------------- #
    def on_burn_alert(self, component: str,
                      data: Optional[Dict[str, Any]] = None
                      ) -> Optional[str]:
        return self.triggers.offer("slo_burn", component, data)

    def on_degraded(self, component: str,
                    detail: Optional[str] = None) -> Optional[str]:
        return self.triggers.offer("watchdog_degraded", component,
                                   {"detail": detail} if detail else None)

    def on_quality_anomaly(self, component: str,
                           data: Optional[Dict[str, Any]] = None
                           ) -> Optional[str]:
        """obs/quality anomaly verdict (NaN storm, dead output, drift
        breach) — fired by the watchdog *before* the generic DEGRADED
        transition so this richer cause wins the rate limit."""
        return self.triggers.offer("quality_anomaly", component, data)

    def on_fleet_action(self, action: str,
                        entry: Optional[Dict[str, Any]] = None
                        ) -> Optional[str]:
        """fleet journal tap; skips/holds are bookkeeping, not
        incidents — only real scale/migrate actions capture."""
        if action not in ("scale_up", "scale_in", "migrate"):
            return None
        return self.triggers.offer("fleet_action", action, entry)

    # -- views ---------------------------------------------------------- #
    def recent_requests(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._requests)

    def critpath(self, min_ms: float = 0.0) -> Dict[str, Any]:
        """The ``GET /debug/diag/critpath`` payload."""
        out = rollup(_tracing.store(), min_ms=min_ms)
        out["requests"] = self.recent_requests()[-64:]
        return out

    def push_doc(self) -> Dict[str, Any]:
        """The fleet push-doc ``diag`` field (obs/fleet.py
        DIAG_PUSH_HOOK): bundle references + trigger accounting, small
        enough to ride every push."""
        return {"bundles": self.bundles.refs(),
                "triggers": dict(self.triggers.stats)}

    def snapshot(self) -> Dict[str, Any]:
        return {
            "bundle_dir": self.bundles.directory,
            "bundles": self.bundles.list(),
            "bundle_stats": dict(self.bundles.stats),
            "triggers": self.triggers.snapshot(),
            "requests": len(self._requests),
            "cost_model": self.cost_model is not None,
        }


# --------------------------------------------------------------------------- #
# enable/disable — the only DIAG_HOOK assignments in the tree
# --------------------------------------------------------------------------- #

def enable(directory: Optional[str] = None, *,
           min_interval_s: float = 30.0, dedup_window_s: float = 300.0,
           z_threshold: float = 4.0, min_samples: int = 16,
           max_bundles: int = 16,
           clock: Callable[[], float] = time.monotonic) -> DiagEngine:
    """Install the diag engine (idempotent). Also flips the obs/fleet
    ``DIAG_PUSH_HOOK`` so push docs start referencing local bundles,
    and anchors the cost-anomaly detector on the tune/ cost model when
    the autotuner is enabled."""
    global DIAG_HOOK
    if DIAG_HOOK is not None:
        return DIAG_HOOK
    from ... import tune as _tune

    tuner = _tune.tuner() if _tune.enabled() else None
    eng = DiagEngine(
        BundleStore(directory or DEFAULT_BUNDLE_DIR,
                    max_bundles=max_bundles),
        min_interval_s=min_interval_s, dedup_window_s=dedup_window_s,
        z_threshold=z_threshold, min_samples=min_samples,
        cost_model=getattr(tuner, "model", None),
        device_kind=_tune.device_kind() if tuner is not None else "",
        clock=clock)
    from .. import fleet as _obsfleet

    _obsfleet.DIAG_PUSH_HOOK = eng.push_doc
    DIAG_HOOK = eng
    return eng


def disable() -> None:
    global DIAG_HOOK
    DIAG_HOOK = None
    from .. import fleet as _obsfleet

    _obsfleet.DIAG_PUSH_HOOK = None


def enabled() -> bool:
    return DIAG_HOOK is not None


def engine() -> Optional[DiagEngine]:
    return DIAG_HOOK


def snapshot() -> Optional[Dict[str, Any]]:
    eng = DIAG_HOOK
    return eng.snapshot() if eng is not None else None


# env enable at import, mirroring NNSTPU_TRACE/PROFILE/...: "1" uses
# the default bundle dir, any other non-empty value IS the dir
_env = os.environ.get("NNSTPU_DIAG", "")
if _env:
    enable(None if _env == "1" else _env)
