"""Critical-path latency attribution over one request's span tree.

The contract is *conservation*: every nanosecond of the root span's
duration lands in exactly one segment, so the segment sums equal the
request's measured latency to the integer. The sweep therefore runs on
raw span timestamps (monotonic ns, incl. remote spans already rebased
by ``SpanStore.ingest_remote``), never on the microsecond floats the
``tree()`` view rounds to.

Attribution rule: split the root interval at every span boundary; each
elementary slice belongs to the *deepest* span covering it (ties: the
latest-starting one — the span that most recently took over the thread
of control). The covering span's name maps to a segment; names the
table doesn't know — and the root's own self-time — fall into
``host_other``, whose share defines the coverage ratio the bench lane
tracks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: attribution buckets, waterfall order — where a request's wall-clock
#: latency can go (host_other is the unexplained residual)
SEGMENTS = ("admission_wait", "sched_wait", "device_compute", "wire",
            "kv_transfer", "migration", "re_prefill", "restore",
            "host_other")

#: span name -> segment. serving.prefill is handled specially (its
#: re_prefill/restore attrs promote it); anything absent here is
#: host_other.
_SEGMENT_BY_NAME = {
    "serving.admission_wait": "admission_wait",
    "diag.sched_wait": "sched_wait",
    "diag.sched_run": "device_compute",
    "serving.prefill": "device_compute",
    "serving.decode": "device_compute",
    "serving.compile": "device_compute",
    "device.xprof": "device_compute",
    "query.send": "wire",
    "query.recv": "wire",
    "disagg.xfer": "kv_transfer",
    "fleet.migrate": "migration",
}


def segment_of(name: str, attrs: Optional[Dict[str, Any]] = None) -> str:
    """Segment for one span; unknown names are host_other."""
    if name == "serving.prefill" and attrs:
        if attrs.get("restore"):
            # first prefill after a crash-restore checkpoint splice —
            # warm by construction; kept distinct from re_prefill so
            # the restore-vs-fallback attribution survives aggregation
            return "restore"
        if attrs.get("re_prefill"):
            return "re_prefill"
    return _SEGMENT_BY_NAME.get(name, "host_other")


def _root_of(spans: List[Any]) -> Optional[Any]:
    """The locally-rooted completed span (parent_id None); earliest
    start wins if a trace somehow holds several roots."""
    roots = [s for s in spans
             if s.context.parent_id is None and s.end_ns is not None]
    if not roots:
        return None
    return min(roots, key=lambda s: s.start_ns)


def analyze(spans: List[Any]) -> Optional[Dict[str, Any]]:
    """Exact segment attribution for one trace's raw spans.

    Returns None for an incomplete trace (no ended root). Otherwise a
    dict whose ``segments`` (ns ints) sum to ``total_ns`` exactly.
    """
    if not spans:
        return None
    root = _root_of(spans)
    if root is None:
        return None
    r0, r1 = root.start_ns, root.end_ns

    # depth via parent links; spans with an unrecorded parent (remote
    # half whose peer span never landed here) hang off the root
    by_id = {s.context.span_id: s for s in spans}
    depth_cache: Dict[str, int] = {root.context.span_id: 0}

    def depth(s: Any) -> int:
        sid = s.context.span_id
        hit = depth_cache.get(sid)
        if hit is not None:
            return hit
        chain = []
        cur = s
        while True:
            cid = cur.context.span_id
            if cid in depth_cache:
                d = depth_cache[cid]
                break
            chain.append(cid)
            parent = by_id.get(cur.context.parent_id or "")
            if parent is None or parent is cur:
                d = 0  # orphan: treated as a root-level child below
                break
            cur = parent
        for cid in reversed(chain):
            d += 1
            depth_cache[cid] = d
        return depth_cache[sid]

    # clip every ended span to the root interval; drop empty clips
    clipped: List[Tuple[int, int, int, int, Any]] = []  # (a, b, depth, seq, span)
    for seq, s in enumerate(spans):
        if s.end_ns is None:
            continue
        a, b = max(s.start_ns, r0), min(s.end_ns, r1)
        if b <= a and s is not root:
            continue
        clipped.append((a, b, depth(s), seq, s))

    bounds = sorted({p for a, b, _, _, _ in clipped for p in (a, b)}
                    | {r0, r1})
    segments = {seg: 0 for seg in SEGMENTS}
    by_span: Dict[str, int] = {}
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo or hi <= r0 or lo >= r1:
            continue
        winner = None
        for a, b, d, seq, s in clipped:
            if a <= lo and b >= hi:
                if winner is None or (d, a, seq) > winner[:3]:
                    winner = (d, a, seq, s)
        if winner is None:
            continue  # unreachable: the root always covers
        s = winner[3]
        seg = segment_of(s.name, s.attrs)
        segments[seg] += hi - lo
        by_span[s.name] = by_span.get(s.name, 0) + (hi - lo)

    total = r1 - r0
    covered = total - segments["host_other"]
    return {
        "trace_id": root.context.trace_id,
        "root": root.name,
        "tenant": _tenant_of(spans, root),
        "total_ns": total,
        "segments": segments,
        "coverage_ratio": (covered / total) if total > 0 else 1.0,
        "contributors": sorted(
            ({"name": n, "segment": segment_of(
                n, next((s.attrs for s in spans if s.name == n), None)),
              "ns": v} for n, v in by_span.items()),
            key=lambda c: c["ns"], reverse=True),
    }


def _tenant_of(spans: List[Any], root: Any) -> str:
    """Best-effort tenant identity: an explicit tenant attr anywhere in
    the tree, else the serving session, else the root's source."""
    for key in ("tenant", "session"):
        for s in spans:
            v = s.attrs.get(key)
            if v:
                return str(v)
    return str(root.attrs.get("source", "-"))


def rollup(store: Any, *, min_ms: float = 0.0,
           max_traces: int = 256) -> Dict[str, Any]:
    """Per-tenant "where does my P99 go" over the store's completed
    traces: aggregate segment shares plus the breakdown of each
    tenant's P99 (slowest-at-rank) request."""
    analyses: List[Dict[str, Any]] = []
    for summ in store.summaries(min_ms=min_ms)[:int(max_traces)]:
        if not summ["completed"]:
            continue
        spans = store.spans_of(summ["trace_id"])
        if not spans:
            continue
        res = analyze(spans)
        if res is not None:
            analyses.append(res)

    tenants: Dict[str, Dict[str, Any]] = {}
    for res in analyses:
        t = tenants.setdefault(res["tenant"], {
            "requests": 0, "total_ns": 0,
            "segments_ns": {seg: 0 for seg in SEGMENTS},
            "_durations": []})
        t["requests"] += 1
        t["total_ns"] += res["total_ns"]
        for seg, ns in res["segments"].items():
            t["segments_ns"][seg] += ns
        t["_durations"].append((res["total_ns"], res))

    for name, t in tenants.items():
        durs = sorted(t.pop("_durations"), key=lambda d: d[0])
        idx = min(len(durs) - 1, int(0.99 * len(durs)))
        p99_total, p99 = durs[idx]
        t["p99_ms"] = p99_total / 1e6
        t["p99_trace"] = {
            "trace_id": p99["trace_id"],
            "total_ms": p99["total_ns"] / 1e6,
            "segments_ms": {seg: ns / 1e6
                            for seg, ns in p99["segments"].items()},
        }
        t["segments_share"] = {
            seg: (ns / t["total_ns"] if t["total_ns"] else 0.0)
            for seg, ns in t["segments_ns"].items()}

    return {
        "traces_analyzed": len(analyses),
        "segments": list(SEGMENTS),
        "tenants": tenants,
    }


def waterfall(result: Dict[str, Any], width: int = 48) -> str:
    """Text waterfall for one ``analyze()`` result — the nns-diag
    rendering and the /debug/diag self-check view."""
    total = max(result["total_ns"], 1)
    lines = [f"trace {result['trace_id']}  root={result['root']}  "
             f"tenant={result['tenant']}  "
             f"total={result['total_ns'] / 1e6:.3f}ms",
             f"coverage={result['coverage_ratio'] * 100:.1f}%"]
    for seg in SEGMENTS:
        ns = result["segments"].get(seg, 0)
        bar = "#" * int(round(width * ns / total))
        lines.append(f"  {seg:<16}{ns / 1e6:>10.3f}ms "
                     f"{100.0 * ns / total:>5.1f}% |{bar}")
    check = sum(result["segments"].values())
    lines.append(f"  {'sum':<16}{check / 1e6:>10.3f}ms "
                 f"({'exact' if check == result['total_ns'] else 'DRIFT'})")
    return "\n".join(lines)
