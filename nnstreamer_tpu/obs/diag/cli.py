"""``nns-diag`` — offline debug-bundle reader.

Loads a bundle captured by :mod:`nnstreamer_tpu.obs.diag` (no live
process needed), prints the critical-path waterfall for the implicated
requests — re-running the exact integer-ns sweep over the bundle's raw
spans, so the offline numbers match what the live endpoint reported —
and optionally emits a Perfetto/Chrome trace of just those requests.

    nns-diag .nnstpu-diag                 # list bundles in a directory
    nns-diag <bundle.json>                # cause + waterfalls
    nns-diag <bundle.json> --trace <tid>  # one request only
    nns-diag <bundle.json> --perfetto out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from . import bundle as _bundle
from . import critpath as _critpath


class _SpanView:
    """Duck-typed stand-in for obs.tracing.Span over a bundle's raw
    span docs — exactly the surface the critpath sweep touches."""

    __slots__ = ("name", "context", "start_ns", "end_ns", "attrs", "wall")

    class _Ctx:
        __slots__ = ("trace_id", "span_id", "parent_id")

        def __init__(self, tid: str, sid: str, par: Optional[str]):
            self.trace_id = tid
            self.span_id = sid
            self.parent_id = par

    def __init__(self, doc: Dict[str, Any]) -> None:
        self.name = str(doc["name"])
        self.context = self._Ctx(str(doc["trace_id"]),
                                 str(doc["span_id"]),
                                 doc.get("parent_id") or None)
        self.start_ns = int(doc["start_ns"])
        self.end_ns = int(doc["end_ns"])
        self.attrs = dict(doc.get("attrs") or {})
        self.wall = float(doc.get("wall") or 0.0)


def _trace_spans(doc: Dict[str, Any]) -> Dict[str, List[_SpanView]]:
    """trace_id -> span views, from the bundle's slowest-N capture."""
    traces = (doc.get("traces") or {}).get("slowest") or []
    out: Dict[str, List[_SpanView]] = {}
    for tr in traces:
        views = []
        for s in tr.get("spans") or []:
            try:
                views.append(_SpanView(s))
            except (KeyError, TypeError, ValueError):
                continue
        if views:
            out[str(tr["trace_id"])] = views
    return out


def _perfetto(traces: Dict[str, List[_SpanView]]) -> Dict[str, Any]:
    """Chrome trace_event JSON of just the implicated requests: one
    process lane per trace, spans as complete ('X') events in µs,
    colored by critical-path segment via the category field."""
    events: List[Dict[str, Any]] = []
    t0 = min((s.start_ns for views in traces.values() for s in views),
             default=0)
    for pid, (tid, views) in enumerate(sorted(traces.items()), start=1):
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": f"trace {tid}"}})
        for s in views:
            events.append({
                "ph": "X", "pid": pid, "tid": 1,
                "name": s.name,
                "cat": _critpath.segment_of(s.name, s.attrs),
                "ts": (s.start_ns - t0) / 1e3,
                "dur": (s.end_ns - s.start_ns) / 1e3,
                "args": s.attrs,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"generator": "nns-diag"}}


def _print_header(doc: Dict[str, Any], out) -> None:
    cause = doc.get("cause") or {}
    build = doc.get("build") or {}
    when = doc.get("wall")
    print(f"bundle {doc.get('id', '?')}", file=out)
    print(f"  cause: {cause.get('kind', 'manual')}"
          f"[{cause.get('key', '')}] {cause.get('detail') or ''}",
          file=out)
    if when:
        print("  captured: "
              + time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(when)),
              file=out)
    if doc.get("instance"):
        print(f"  instance: {doc['instance']}", file=out)
    if isinstance(build, dict) and build.get("version"):
        print(f"  build: {build.get('version')} "
              f"(jax {build.get('jax', '?')}, "
              f"device {build.get('device_kind', '?')})", file=out)


def _list_dir(directory: str, out) -> int:
    store = _bundle.BundleStore(directory)
    entries = store.list()
    if not entries:
        print(f"no bundles in {directory}", file=out)
        return 1
    for e in entries:
        cause = e.get("cause") or {}
        print(f"{e['id']:<48} {cause.get('kind', '?'):<18} "
              f"{e.get('bytes', 0):>9}B", file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-diag",
        description="inspect nnstreamer_tpu debug bundles offline")
    ap.add_argument("target",
                    help="bundle .json file, or a bundle directory to list")
    ap.add_argument("--trace", metavar="TID", default=None,
                    help="restrict to one trace id")
    ap.add_argument("--perfetto", metavar="PATH", default=None,
                    help="write a Perfetto/Chrome trace of the "
                    "implicated requests")
    ap.add_argument("--max-traces", type=int, default=8,
                    help="waterfalls to print (default 8)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable critpath output")
    args = ap.parse_args(argv)
    out = sys.stdout

    if os.path.isdir(args.target):
        return _list_dir(args.target, out)
    try:
        doc = _bundle.load_bundle(args.target)
    except (OSError, ValueError) as e:
        print(f"nns-diag: {e}", file=sys.stderr)
        return 2

    traces = _trace_spans(doc)
    if args.trace is not None:
        traces = {k: v for k, v in traces.items() if k == args.trace}
        if not traces:
            print(f"nns-diag: trace {args.trace!r} not in bundle",
                  file=sys.stderr)
            return 2

    results = []
    for tid, views in traces.items():
        res = _critpath.analyze(views)
        if res is not None:
            results.append(res)
    results.sort(key=lambda r: r["total_ns"], reverse=True)
    results = results[:max(args.max_traces, 0)]

    if args.json:
        json.dump({"id": doc.get("id"), "cause": doc.get("cause"),
                   "critpath": results}, out, indent=2, default=str)
        print(file=out)
    else:
        _print_header(doc, out)
        if not results:
            print("  (no analyzable traces in bundle)", file=out)
        for res in results:
            print(file=out)
            print(_critpath.waterfall(res), file=out)

    if args.perfetto:
        keep = {r["trace_id"] for r in results}
        doc_pf = _perfetto({k: v for k, v in traces.items() if k in keep})
        with open(args.perfetto, "w") as f:
            json.dump(doc_pf, f)
        print(f"wrote {args.perfetto} "
              f"({len(doc_pf['traceEvents'])} events)", file=out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
