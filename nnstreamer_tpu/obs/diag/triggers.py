"""Incident trigger engine: decide *when* a debug bundle is worth the
disk, without letting a flapping alert turn the bundle directory into
a second event ring.

Five cause kinds feed :meth:`TriggerEngine.offer`:

- ``slo_burn``          — obs/slo.py burn alert (key: component)
- ``watchdog_degraded`` — obs/health.py DEGRADED verdict (key: component)
- ``fleet_action``      — fleet/controller.py scale/migrate (key: action)
- ``cost_anomaly``      — measured sched dispatch time vs the tune/
  cost-model expectation (or the label's own running mean when the
  model doesn't cover it), z-score above threshold (key: label)
- ``quality_anomaly``   — obs/quality data-plane verdict (NaN storm,
  dead output, drift breach) at the watchdog (key: component)

Two independent brakes, both on an injectable clock so the
determinism test drives them by hand:

- **rate limit**: at most one capture per ``min_interval_s``, globally
  — bundles are heavyweight, causes are not.
- **dedup by cause**: the same (kind, key) within ``dedup_window_s``
  is the same incident; one bundle carries it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

#: causes offer() understands — anything else is rejected loudly in
#: tests and silently dropped in production paths
CAUSE_KINDS = ("slo_burn", "watchdog_degraded", "fleet_action",
               "cost_anomaly", "quality_anomaly")


class _Welford:
    """Running mean/variance for one dispatch label."""

    __slots__ = ("n", "mean", "m2")

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def add(self, x: float) -> None:
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self.m2 += d * (x - self.mean)

    def std(self) -> float:
        if self.n < 2:
            return 0.0
        return (self.m2 / (self.n - 1)) ** 0.5


class TriggerEngine:
    """Rate-limited, deduplicating trigger front-end for bundle capture.

    ``capture`` is called as ``capture(cause: dict)`` and returns a
    bundle id (or None when capture itself declined); the engine never
    raises out of ``offer`` — a sick diag layer must not take serving
    down with it.
    """

    def __init__(self, capture: Callable[[Dict[str, Any]], Optional[str]],
                 *, min_interval_s: float = 30.0,
                 dedup_window_s: float = 300.0,
                 z_threshold: float = 4.0, min_samples: int = 16,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._capture = capture
        self.min_interval_s = float(min_interval_s)
        self.dedup_window_s = float(dedup_window_s)
        self.z_threshold = float(z_threshold)
        self.min_samples = int(min_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_fire: Optional[float] = None
        self._seen: Dict[Tuple[str, str], float] = {}  # (kind, key) -> t
        self._cost: Dict[str, _Welford] = {}
        self.stats: Dict[str, int] = {
            "offered": 0, "fired": 0, "rate_limited": 0, "deduped": 0,
            "capture_declined": 0}

    # -- the decision ------------------------------------------------- #
    def offer(self, kind: str, key: str,
              detail: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """One observed cause. Returns the bundle id when a capture
        fired, None when braked (or the cause kind is unknown)."""
        if kind not in CAUSE_KINDS:
            return None
        now = self._clock()
        with self._lock:
            self.stats["offered"] += 1
            seen_t = self._seen.get((kind, key))
            if seen_t is not None and now - seen_t < self.dedup_window_s:
                self.stats["deduped"] += 1
                return None
            if self._last_fire is not None \
                    and now - self._last_fire < self.min_interval_s:
                self.stats["rate_limited"] += 1
                return None
            # claim the slot before the (slow) capture runs so a
            # concurrent cause can't double-fire
            self._last_fire = now
            self._seen[(kind, key)] = now
            if len(self._seen) > 1024:
                cutoff = now - self.dedup_window_s
                self._seen = {k: t for k, t in self._seen.items()
                              if t >= cutoff}
        cause = {"kind": kind, "key": key, "t": now,
                 "detail": dict(detail or {})}
        try:
            bundle_id = self._capture(cause)
        except Exception:
            bundle_id = None
        with self._lock:
            if bundle_id is None:
                self.stats["capture_declined"] += 1
            else:
                self.stats["fired"] += 1
        return bundle_id

    # -- cost-model anomaly detection --------------------------------- #
    def observe_cost(self, label: str, measured_us: float,
                     expected_us: Optional[float] = None
                     ) -> Optional[str]:
        """One measured dispatch. With a tune/ prediction, the residual
        (measured - expected) feeds the label's running distribution;
        without one, the raw measurement does. A sample more than
        ``z_threshold`` standard deviations above the mean — after
        ``min_samples`` sightings — is a cost anomaly."""
        x = float(measured_us) - float(expected_us or 0.0)
        with self._lock:
            w = self._cost.get(label)
            if w is None:
                w = self._cost[label] = _Welford()
                if len(self._cost) > 512:  # label-cardinality bound
                    self._cost.pop(next(iter(self._cost)))
            n, mean, std = w.n, w.mean, w.std()
            w.add(x)
        if n < self.min_samples or std <= 0.0:
            return None
        z = (x - mean) / std
        if z < self.z_threshold:
            return None
        return self.offer("cost_anomaly", label, {
            "measured_us": float(measured_us),
            "expected_us": float(expected_us) if expected_us else None,
            "z": round(z, 2), "mean_us": round(mean, 2),
            "std_us": round(std, 2), "samples": n})

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stats": dict(self.stats),
                "min_interval_s": self.min_interval_s,
                "dedup_window_s": self.dedup_window_s,
                "z_threshold": self.z_threshold,
                "tracked_labels": len(self._cost),
                "recent_causes": sorted(
                    (f"{k[0]}:{k[1]}" for k in self._seen), )[:32],
            }
