"""nnstreamer_tpu.obs — unified metrics, tracing, health & exposition.

Always-on counters/gauges/histograms fed by the pipeline graph, the
query offload layer, and the serving engines, with a stdlib HTTP
``/metrics`` + ``/healthz`` + ``/readyz`` endpoint — plus span-based
request tracing with cross-wire context propagation and tail-based
retention (``/debug/traces``, ``/debug/pipeline``), a component health
model with a stall watchdog driving the real ``/healthz``/``/readyz``
verdicts, and a flight-recorder event ring (``/debug/events``). See
docs/observability.md for the metric/span/event name catalogs and
usage.

Metrics, tracing, health, events, and profiling are independently
switchable (``enable()`` / ``tracing.enable()`` / ``health.enable()``
/ ``events.enable()`` / ``profile.enable()``); each is a flag-check
no-op when off. The fleet layer (obs/fleet.py) federates metrics,
health, and spans across processes: workers push snapshots over the
query wire or plain HTTP, and one aggregator re-exposes the merged
fleet on its exporter. The profiler (obs/profile.py) adds device-time
attribution: per-dispatch host/device timing, jit-cache and compile
telemetry, live MFU/roofline gauges, and a Perfetto timeline at
``/debug/profile``. The SLO layer (obs/slo.py) adds per-tenant cost
attribution, goodput accounting, and multi-window burn-rate alerting
surfaced at ``/debug/slo``.
"""

from .metrics import (DEFAULT_LATENCY_BUCKETS, MetricsRegistry, disable,
                      enable, enabled, registry)
from .exporter import MetricsExporter, start_exporter
from .instrument import instrument_pipeline
from . import events
from . import fleet
from . import health
from . import profile
from . import slo
from . import tracing
from .events import EventRing
from .fleet import FleetAggregator, FleetPusher
from .health import Component, HealthRegistry, Status
from .profile import Profiler, perfetto_trace
from .tracing import Span, SpanContext, SpanStore, start_span

__all__ = [
    "Component", "DEFAULT_LATENCY_BUCKETS", "EventRing",
    "FleetAggregator", "FleetPusher", "HealthRegistry",
    "MetricsRegistry", "MetricsExporter", "Profiler", "Span",
    "SpanContext", "SpanStore", "Status", "disable", "enable",
    "enabled", "events", "fleet", "health", "instrument_pipeline",
    "perfetto_trace", "profile", "registry", "slo", "start_exporter",
    "start_span", "tracing",
]
