"""nnstreamer_tpu.obs — unified metrics & exposition subsystem.

Always-on counters/gauges/histograms fed by the pipeline graph, the
query offload layer, and the serving engines, with a stdlib HTTP
``/metrics`` + ``/healthz`` endpoint. See docs/observability.md for
the metric name catalog and usage.
"""

from .metrics import (DEFAULT_LATENCY_BUCKETS, MetricsRegistry, disable,
                      enable, enabled, registry)
from .exporter import MetricsExporter, start_exporter
from .instrument import instrument_pipeline

__all__ = [
    "DEFAULT_LATENCY_BUCKETS", "MetricsRegistry", "MetricsExporter",
    "disable", "enable", "enabled", "instrument_pipeline", "registry",
    "start_exporter",
]
