"""nnstreamer_tpu.obs — unified metrics, tracing & exposition subsystem.

Always-on counters/gauges/histograms fed by the pipeline graph, the
query offload layer, and the serving engines, with a stdlib HTTP
``/metrics`` + ``/healthz`` endpoint — plus span-based request tracing
with cross-wire context propagation and tail-based retention, exposed
at ``/debug/traces`` and ``/debug/pipeline``. See docs/observability.md
for the metric name catalog, the span catalog, and usage.

Metrics and tracing are independently switchable (``enable()`` /
``tracing.enable()``); both are flag-check no-ops when off.
"""

from .metrics import (DEFAULT_LATENCY_BUCKETS, MetricsRegistry, disable,
                      enable, enabled, registry)
from .exporter import MetricsExporter, start_exporter
from .instrument import instrument_pipeline
from . import tracing
from .tracing import Span, SpanContext, SpanStore, start_span

__all__ = [
    "DEFAULT_LATENCY_BUCKETS", "MetricsRegistry", "MetricsExporter",
    "Span", "SpanContext", "SpanStore", "disable", "enable", "enabled",
    "instrument_pipeline", "registry", "start_exporter", "start_span",
    "tracing",
]
