"""Device-time profiling, compile/roofline telemetry, and Perfetto
trace export.

The obs stack up to here answers *whether* a request was slow (metrics),
*which* request (traces), and *who* is unhealthy (health/fleet). This
module answers *where the device time went*:

  * **Dispatch records** — every XLA filter dispatch is timed on the
    host (submit → return), and every Nth dispatch is additionally
    synced with ``block_until_ready`` to measure true device execution
    time plus the dispatch-queue gap since the previous dispatch of the
    same bundle. Records land in a bounded ring (SpanStore-style).
  * **Compile observability** — jit executable-cache hit/miss counters
    (both the bundle-metadata cache in filters/xla and the per-shape
    executable cache), compile-duration histograms, and per-compiled-
    function HLO ``cost_analysis()`` (FLOPs, bytes accessed) captured
    once per (bundle, shape-signature).
  * **Live MFU / roofline gauges** — per-engine achieved-FLOP/s EWMA
    over ``chip_peak_flops`` and operational intensity over the chip's
    ridge intensity, exported on ``/metrics`` as
    ``nnstpu_profile_mfu_ratio{engine=...}`` and friends. Until now
    these numbers existed only in one-shot bench.py runs.
  * **Perfetto timeline** — ``perfetto_trace()`` renders host lanes
    (one per pipeline thread, from SpanStore spans), device lanes (one
    per bundle/kernel label, from profiler records), and serving lanes
    (per-phase rows plus a batch-occupancy counter track) as Chrome
    ``trace_event`` JSON, served at ``GET /debug/profile``.
  * **Autotuner substrate** — aggregated ``(label, shapes, dtypes,
    device) → cost`` samples (``samples()`` / ``dump_samples()``), the
    training-data format the ROADMAP-4 learned autotuner consumes.

Zero-overhead-when-off contract (the chaos-hook pattern): consumers
gate on module-global hooks that are ``None`` unless profiling is on —

    if _profile.DISPATCH_HOOK is not None:   # one load + None check
        outs = _profile.DISPATCH_HOOK.dispatch(self, arrays)
    else:
        outs = self._jitted(*arrays)

``enable()`` installs the hooks (including ``PROFILE_CHAIN_HOOK`` in
graph/element.py for host-lane fallback timing when tracing is off);
``disable()`` clears them. ``NNSTPU_PROFILE=1`` enables at import, and
``nns-launch --profile[=N]`` from the CLI.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import events as _events
from . import metrics as _metrics
from . import quality as _quality
from . import slo as _slo
from . import tracing as _tracing

__all__ = [
    "Profiler", "profiler", "enabled", "enable", "disable",
    "perfetto_trace", "samples", "dump_samples", "report",
    "DISPATCH_HOOK", "ENGINE_HOOK", "KERNEL_HOOK", "SCHED_HOOK",
]

#: Hook consumed by filters/xla.py around ``self._jitted(*arrays)``.
#: The active Profiler when profiling is on, else None — dispatch sites
#: pay one module-attribute load + None check when off.
DISPATCH_HOOK: Optional["Profiler"] = None

#: Hook consumed by serving/lm_engine.py (TPLMEngine inherits the call
#: sites) to record prefill/decode/verify phase timings + occupancy.
ENGINE_HOOK: Optional["Profiler"] = None

#: Hook consumed by ops/pallas entry points at trace time: records
#: which Pallas kernels (label, shape, dtype) end up inside compiled
#: programs — device-lane labels for fused dispatches.
KERNEL_HOOK = None  # Optional[Callable[[str, Any, Any], None]]

#: Hook consumed by sched/engine.py after each coalesced device batch:
#: records per-batch dispatch intervals (engine lane, coalesce width,
#: tenants served, queue depth) so the multiplexed dispatch stream gets
#: its own Perfetto process group next to host/device/serving.
SCHED_HOOK: Optional["Profiler"] = None

#: default ring capacity / sync-probe cadence (every Nth dispatch pays
#: a block_until_ready to measure device time)
DEFAULT_MAX_RECORDS = 4096
DEFAULT_SAMPLE_EVERY = 8


def _cost_dict(ca: Any) -> Dict[str, float]:
    """Normalize ``compiled.cost_analysis()`` (dict, or [dict] on older
    jax) into {"flops": float, "bytes": float}."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": 0.0, "bytes": 0.0}
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes": float(ca.get("bytes accessed", 0.0) or 0.0),
    }


class Profiler:
    """Bounded, lock-protected store of dispatch/engine/kernel records
    plus the derived live telemetry (jit-cache counters, compile
    histograms, MFU/roofline gauges, autotuner samples).

    All recording methods are reached only through the module hooks, so
    none of them is on any hot path while profiling is off."""

    def __init__(self, max_records: int = DEFAULT_MAX_RECORDS,
                 sample_every: int = DEFAULT_SAMPLE_EVERY,
                 enabled: bool = False):
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=int(max_records))
        self.sample_every = max(1, int(sample_every))
        self._enabled = bool(enabled)
        self._n_dispatch = 0            # guarded-by: _lock
        self._dropped = 0               # guarded-by: _lock
        self._last_done_ns: Dict[str, int] = {}   # guarded-by: _lock
        # (label, shapes, dtypes, device) -> aggregate cost sample
        self._samples: Dict[Tuple, Dict[str, Any]] = {}  # guarded-by: _lock
        # per-shape executable-cache key -> {"flops","bytes"} (or None
        # while a capture is in flight / unavailable)
        self._cost_seen: Dict[Tuple, Optional[Dict[str, float]]] = {}
        # utilization state per lane name ("lm", "tp", "xla")
        self._util: Dict[str, Dict[str, float]] = {}
        self._params_cache: Dict[int, float] = {}  # id(engine) -> n_params
        self._peak_cache: Optional[Tuple[float, float]] = None
        self._m: Optional[Dict[str, Any]] = None

    # -- lifecycle ------------------------------------------------------ #
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def resize(self, max_records: int) -> None:
        with self._lock:
            self._records = deque(self._records, maxlen=int(max_records))

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._samples.clear()
            self._cost_seen.clear()
            self._util.clear()
            self._last_done_ns.clear()
            self._n_dispatch = 0
            self._dropped = 0

    # -- metric families ------------------------------------------------ #
    def _register_metrics(self) -> None:
        """Idempotent: registry._register returns the existing family."""
        reg = _metrics.registry()
        self._m = {
            "jit": reg.counter(
                "nnstpu_profile_jit_cache_total",
                "jit executable/bundle cache lookups", ("site", "event")),
            "compile": reg.histogram(
                "nnstpu_profile_compile_seconds",
                "XLA trace+compile durations", ("site",)),
            "dispatch": reg.histogram(
                "nnstpu_profile_dispatch_seconds",
                "profiled dispatch durations by record kind and clock "
                "(device = block_until_ready-synced probe)",
                ("kind", "clock")),
            "mfu": reg.gauge(
                "nnstpu_profile_mfu_ratio",
                "achieved FLOP/s EWMA over chip peak, per lane",
                ("engine",)),
            "roofline": reg.gauge(
                "nnstpu_profile_roofline_ratio",
                "operational intensity over chip ridge intensity "
                "(<1 memory-bound, >1 compute-bound)", ("engine",)),
            "achieved": reg.gauge(
                "nnstpu_profile_achieved_flops",
                "achieved FLOP/s EWMA, per lane", ("engine",)),
        }
        # re-attach collection callbacks for lanes that already exist
        # (enable → disable → enable keeps prior state readable)
        for name in list(self._util):
            self._attach_util_gauges(name)

    # -- peak / roofline ------------------------------------------------ #
    def _peaks(self) -> Tuple[float, float]:
        """(peak FLOP/s, peak HBM bytes/s) for device 0, cached."""
        if self._peak_cache is None:
            try:
                import jax

                from ..utils import probes
                dev = jax.devices()[0]
                self._peak_cache = (probes.chip_peak_flops(dev),
                                    probes.chip_peak_hbm_bw(dev))
            except Exception:
                self._peak_cache = (0.0, 0.0)
        return self._peak_cache

    def _mfu_of(self, name: str) -> float:
        peak, _ = self._peaks()
        st = self._util.get(name)
        return (st["flops_s"] / peak) if (st and peak) else 0.0

    def _roofline_of(self, name: str) -> float:
        peak, bw = self._peaks()
        st = self._util.get(name)
        if not st or not peak or not bw or not st["intensity"]:
            return 0.0
        return st["intensity"] / (peak / bw)

    def _achieved_of(self, name: str) -> float:
        st = self._util.get(name)
        return st["flops_s"] if st else 0.0

    def _attach_util_gauges(self, name: str) -> None:
        if self._m is None:
            return
        self._m["mfu"].labels(name).set_function(
            lambda n=name: self._mfu_of(n))
        self._m["roofline"].labels(name).set_function(
            lambda n=name: self._roofline_of(n))
        self._m["achieved"].labels(name).set_function(
            lambda n=name: self._achieved_of(n))

    def _update_util(self, name: str, flops: float, bytes_: float,
                     dt_s: float) -> None:
        """Fold one measured interval into the lane's achieved-FLOP/s
        EWMA + operational intensity (drives the live gauges)."""
        if dt_s <= 0.0 or flops <= 0.0:
            return
        with self._lock:
            st = self._util.get(name)
            fresh = st is None
            if fresh:
                st = self._util[name] = {
                    "flops_s": 0.0, "intensity": 0.0, "n": 0}
            achieved = flops / dt_s
            alpha = 0.25
            st["flops_s"] = achieved if st["n"] == 0 else \
                (1.0 - alpha) * st["flops_s"] + alpha * achieved
            if bytes_ > 0.0:
                st["intensity"] = flops / bytes_
            st["n"] += 1
        if fresh:
            self._attach_util_gauges(name)

    # -- ring ----------------------------------------------------------- #
    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._records) == self._records.maxlen:
                self._dropped += 1
            self._records.append(rec)

    def records(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._records)
        return recs if kind is None else [r for r in recs
                                          if r["kind"] == kind]

    def diag_snapshot(self, max_records: int = 256) -> Dict[str, Any]:
        """Bounded freeze for obs.diag debug bundles: full stats and
        aggregated samples, but only the newest ``max_records`` raw
        records — a bundle must stay shippable, and the raw ring can
        hold tens of thousands of dispatch rows."""
        recs = self.records()
        return {
            "enabled": self._enabled,
            "stats": self.stats(),
            "records_total": len(recs),
            "records": recs[-max_records:],
            "samples": self.samples(),
        }

    # -- compile observability (filters/xla.py) ------------------------- #
    def on_jit_cache(self, site: str, hit: bool) -> None:
        """Count a jit-cache lookup. site="bundle" is the metadata-level
        cache in _build_jit; site="executable" the per-shape cache."""
        if self._m is not None:
            self._m["jit"].labels(site, "hit" if hit else "miss").inc()

    def record_compile(self, site: str, seconds: float) -> None:
        if self._m is not None:
            self._m["compile"].labels(site).observe(seconds)

    def _cost_for(self, key: Tuple, jitted: Any, arrays: Any,
                  label: str) -> Optional[Dict[str, float]]:
        """HLO cost for (bundle, shape-sig), captured once. The first
        sight of a key lowers+compiles ahead of the call — that timed
        compile both feeds the compile histogram and warms jax's own
        executable cache, so the dispatch right after runs compiled."""
        with self._lock:
            if key in self._cost_seen:
                hit = True
                cost = self._cost_seen[key]
            else:
                hit = False
                cost = self._cost_seen[key] = None
        self.on_jit_cache("executable", hit)
        if hit:
            return cost
        if not hasattr(jitted, "lower"):   # jit=False bundles are lambdas
            return None
        try:
            t0 = time.monotonic()
            compiled = jitted.lower(*arrays).compile()
            self.record_compile("xla", time.monotonic() - t0)
            cost = _cost_dict(compiled.cost_analysis())
        except Exception:
            return None
        with self._lock:
            self._cost_seen[key] = cost
        return cost

    # -- dispatch recording (filters/xla.py) ---------------------------- #
    def dispatch(self, bundle: Any, arrays: List[Any],
                 fn: Any = None) -> Any:
        """Run ``bundle._jitted(*arrays)`` under the profiler: host
        timing always, device timing (block_until_ready) every Nth
        dispatch, HLO cost once per shape signature. Called with the
        bundle's dispatch lock held — same exclusion as the bare call.
        ``fn`` overrides the callable while keeping the bundle's label
        and sample key (filters/xla.py's donating coalesce twin)."""
        jitted = fn if fn is not None else bundle._jitted
        label = getattr(bundle, "_epilogue_label", None) \
            or getattr(getattr(bundle, "_bundle", None), "name", None) \
            or type(bundle).__name__
        shapes = tuple(tuple(int(d) for d in a.shape) for a in arrays)
        dtypes = tuple(str(a.dtype) for a in arrays)
        key = (label, shapes, dtypes)
        # cost BEFORE the call: with donation on, input buffers are
        # dead afterwards and must not be re-lowered
        cost = self._cost_for(key, jitted, arrays, label)
        with self._lock:
            self._n_dispatch += 1
            sync = self._n_dispatch % self.sample_every == 0
            last = self._last_done_ns.get(label)
        t0 = time.monotonic_ns()
        outs = jitted(*arrays)
        t1 = time.monotonic_ns()
        device_ns = None
        if sync:
            try:
                import jax
                jax.block_until_ready(outs)
                device_ns = time.monotonic_ns() - t0
            except Exception:
                device_ns = None
        done = time.monotonic_ns()
        gap_ns = max(t0 - last, 0) if last is not None else None
        with self._lock:
            self._last_done_ns[label] = done
        self._record_sample(key, t1 - t0, device_ns, cost, arrays)
        self._append({
            "kind": "dispatch", "label": label, "t0_ns": t0,
            "dur_ns": t1 - t0, "device_ns": device_ns, "gap_ns": gap_ns,
            "tid": threading.get_ident(),
            "args": {"shapes": shapes, "dtypes": dtypes,
                     **({"flops": cost["flops"], "bytes": cost["bytes"]}
                        if cost else {})},
        })
        if self._m is not None:
            self._m["dispatch"].labels("xla", "host").observe(
                (t1 - t0) / 1e9)
            if device_ns is not None:
                self._m["dispatch"].labels("xla", "device").observe(
                    device_ns / 1e9)
        if cost and device_ns:
            self._update_util("xla", cost["flops"], cost["bytes"],
                              device_ns / 1e9)
        return outs

    def dispatch_fn(self, label: str, fn: Any, *arrays: Any) -> Any:
        """Profiled dispatch for auxiliary jits that are not XLAFilter
        bundles — unfused transform-element math and decoder device
        reduces. Each call appends one kind="dispatch" record under the
        caller's explicit label, so dispatches-per-frame on a pipeline is
        simply the dispatch-record count over the frame count."""
        shapes = tuple(tuple(int(d) for d in a.shape) for a in arrays)
        dtypes = tuple(str(a.dtype) for a in arrays)
        with self._lock:
            self._n_dispatch += 1
            sync = self._n_dispatch % self.sample_every == 0
            last = self._last_done_ns.get(label)
        t0 = time.monotonic_ns()
        outs = fn(*arrays)
        t1 = time.monotonic_ns()
        device_ns = None
        if sync:
            try:
                import jax
                jax.block_until_ready(outs)
                device_ns = time.monotonic_ns() - t0
            except Exception:
                device_ns = None
        done = time.monotonic_ns()
        gap_ns = max(t0 - last, 0) if last is not None else None
        with self._lock:
            self._last_done_ns[label] = done
        self._append({
            "kind": "dispatch", "label": str(label), "t0_ns": t0,
            "dur_ns": t1 - t0, "device_ns": device_ns, "gap_ns": gap_ns,
            "tid": threading.get_ident(),
            "args": {"shapes": shapes, "dtypes": dtypes},
        })
        if self._m is not None:
            self._m["dispatch"].labels("xla", "host").observe(
                (t1 - t0) / 1e9)
            if device_ns is not None:
                self._m["dispatch"].labels("xla", "device").observe(
                    device_ns / 1e9)
        return outs

    # -- epilogue fusion advice (ops/epilogue.py) ----------------------- #
    def epilogue_select(self, filter_label: str,
                        chain_labels: List[str]) -> bool:
        """Cost-sample-driven fuse/don't-fuse advice for one candidate
        chain. With no host-lane element records for the chain's stages
        (cold profiler, fresh pipeline) fusion proceeds unconditionally —
        the fused program is never slower than per-stage dispatch unless
        the stages were already free. Only when observed element records
        say the whole chain costs under ~1µs of host time combined do we
        decline, keeping the jit-cache signature stable for nothing."""
        del filter_label
        per: Dict[str, List[int]] = {}
        for r in self.records(kind="element"):
            per.setdefault(r["label"], []).append(int(r["dur_ns"]))
        seen = [per[c] for c in chain_labels if c in per]
        if not seen:
            return True
        combined = sum(sum(d) / len(d) for d in seen)
        return combined >= 1_000.0

    def _device_kind(self, arrays: Any) -> str:
        for a in arrays:
            dev = getattr(a, "device", None) or (
                getattr(a, "devices", lambda: None)() or [None])
            if isinstance(dev, (set, list, tuple)):
                dev = next(iter(dev), None)
            kind = getattr(dev, "device_kind", None)
            if kind:
                return str(kind)
        return "unknown"

    def _record_sample(self, key: Tuple, host_ns: int,
                       device_ns: Optional[int],
                       cost: Optional[Dict[str, float]],
                       arrays: Any) -> None:
        """Fold one dispatch into the (shape, dtype, fusion, device) →
        cost aggregate — the autotuner's training substrate."""
        label, shapes, dtypes = key
        with self._lock:
            skey = key
            s = self._samples.get(skey)
            if s is None:
                s = self._samples[skey] = {
                    "label": label, "shapes": shapes, "dtypes": dtypes,
                    "device": self._device_kind(arrays),
                    "n": 0, "host_ns": 0, "device_ns": 0, "device_n": 0,
                    "flops": 0.0, "bytes": 0.0,
                }
                if cost:
                    s["flops"] = cost["flops"]
                    s["bytes"] = cost["bytes"]
            s["n"] += 1
            s["host_ns"] += int(host_ns)
            if device_ns is not None:
                s["device_ns"] += int(device_ns)
                s["device_n"] += 1

    # -- engine recording (serving/lm_engine.py) ------------------------ #
    def _engine_params(self, engine: Any) -> float:
        key = id(engine)
        n = self._params_cache.get(key)
        if n is None:
            try:
                import jax
                n = float(sum(
                    int(getattr(x, "size", 0) or 0)
                    for x in jax.tree_util.tree_leaves(engine.params)))
            except Exception:
                n = 0.0
            self._params_cache[key] = n
        return n

    def record_engine(self, engine: Any, phase: str, t0_ns: int,
                      t1_ns: int, *, tokens: int = 0, steps: int = 1,
                      active: Optional[int] = None,
                      queued: Optional[int] = None,
                      slots: Optional[int] = None,
                      compiled: bool = False,
                      **attrs: Any) -> None:
        """One engine phase interval (prefill / decode / verify). The
        interval ends on a host-blocking D2H, so wall duration ≈ device
        time for the phase. Decode FLOPs use the analytic 2·N·tokens
        lower bound (N = param count); bytes model one weight read per
        step — the standard decode roofline."""
        name = str(getattr(engine, "_engine_label", "lm"))
        dur_ns = max(int(t1_ns - t0_ns), 0)
        nparams = self._engine_params(engine)
        flops = 2.0 * nparams * float(tokens)
        bytes_ = 4.0 * nparams * float(max(steps, 1))
        args: Dict[str, Any] = {"tokens": tokens, "steps": steps, **attrs}
        if active is not None:
            args.update(active=active, queued=queued, slots=slots)
        self._append({
            "kind": "engine", "label": f"{name}.{phase}", "t0_ns": t0_ns,
            "dur_ns": dur_ns, "device_ns": dur_ns, "gap_ns": None,
            "tid": threading.get_ident(), "args": args,
        })
        if active is not None:
            self._append({
                "kind": "occupancy", "label": name, "t0_ns": t1_ns,
                "dur_ns": 0, "device_ns": None, "gap_ns": None,
                "tid": 0,
                "args": {"active": int(active), "queued": int(queued or 0),
                         "slots": int(slots or 0)},
            })
        if self._m is not None:
            self._m["dispatch"].labels("engine", "host").observe(
                dur_ns / 1e9)
            if compiled:
                self._m["compile"].labels("engine").observe(dur_ns / 1e9)
        if not compiled:  # first-use intervals are compile, not compute
            self._update_util(name, flops, bytes_, dur_ns / 1e9)

    # -- scheduler batches (sched/engine.py SCHED_HOOK) ----------------- #
    def record_sched(self, engine: str, label: str, t0_ns: int,
                     t1_ns: int, *, width: int = 1,
                     tenants: Optional[Sequence[str]] = None,
                     queued: int = 0, inflight: int = 0) -> None:
        """One coalesced device batch from a DeviceEngine dispatch loop:
        the interval covers dispatch through result scatter (host view;
        device time for the batch shows on the device lane's dispatch
        record). ``width`` is the coalesce width, ``tenants`` the names
        served, ``queued``/``inflight`` the post-batch engine state —
        rendered as both a slice lane per work label and a counter
        track, so dispatch-queue gaps and multiplexing density read
        straight off the trace."""
        self._append({
            "kind": "sched", "label": f"{engine}.{label}",
            "t0_ns": t0_ns, "dur_ns": max(int(t1_ns - t0_ns), 0),
            "device_ns": None, "gap_ns": None,
            "tid": threading.get_ident(),
            "args": {"engine": engine, "width": int(width),
                     "tenants": list(tenants or ()),
                     "queued": int(queued), "inflight": int(inflight)},
        })
        if self._m is not None:
            self._m["dispatch"].labels("sched", "host").observe(
                max(t1_ns - t0_ns, 0) / 1e9)

    # -- kernel labels (ops/pallas) ------------------------------------- #
    def record_kernel(self, name: str, shape: Any, dtype: Any) -> None:
        """Trace-time Pallas kernel label: which kernels (with what
        shapes) ended up inside compiled programs. Fires while jax is
        tracing, so shapes may come from tracers — only static shape
        and dtype are touched."""
        try:
            shp = tuple(int(d) for d in shape)
        except Exception:
            shp = ()
        self._append({
            "kind": "kernel", "label": str(name),
            "t0_ns": time.monotonic_ns(), "dur_ns": 0,
            "device_ns": None, "gap_ns": None,
            "tid": threading.get_ident(),
            "args": {"shape": shp, "dtype": str(dtype)},
        })

    # -- host-lane fallback (graph/element.py PROFILE_CHAIN_HOOK) ------- #
    def profiled_chain(self, peer: Any, buf: Any) -> Any:
        """Timed stand-in for ``peer.element._chain_entry(peer, buf)``:
        host-lane records per element when tracing is off (with tracing
        on, pipeline.element spans already cover the host lanes)."""
        t0 = time.monotonic_ns()
        ret = peer.element._chain_entry(peer, buf)
        t1 = time.monotonic_ns()
        self._append({
            "kind": "element", "label": str(peer.element.name),
            "t0_ns": t0, "dur_ns": t1 - t0, "device_ns": None,
            "gap_ns": None, "tid": threading.get_ident(), "args": {},
        })
        if self._m is not None:
            self._m["dispatch"].labels("element", "host").observe(
                (t1 - t0) / 1e9)
        return ret

    # -- derived views --------------------------------------------------- #
    def samples(self) -> List[Dict[str, Any]]:
        """Aggregated cost samples, slowest mean device time first."""
        with self._lock:
            out = [dict(s) for s in self._samples.values()]
        for s in out:
            s["mean_host_us"] = (s["host_ns"] / s["n"] / 1e3) if s["n"] \
                else 0.0
            s["mean_device_us"] = (s["device_ns"] / s["device_n"] / 1e3) \
                if s["device_n"] else None
        out.sort(key=lambda s: -(s["mean_device_us"] or s["mean_host_us"]))
        return out

    def dump_samples(self, path: str) -> int:
        """Persist the (shape, dtype, fusion, device) → cost records —
        the ROADMAP-4 autotuner's training data. Returns the count."""
        rows = self.samples()
        with open(path, "w", encoding="utf-8") as fp:
            json.dump({"version": 1, "samples": rows}, fp, indent=1,
                      default=str)
        return len(rows)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            kinds: Dict[str, int] = {}
            for r in self._records:
                kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
            return {
                "enabled": self._enabled,
                "records": len(self._records),
                "dropped": self._dropped,
                "dispatches": self._n_dispatch,
                "by_kind": kinds,
                "sample_every": self.sample_every,
                "lanes": {n: dict(st) for n, st in self._util.items()},
            }

    def report(self) -> str:
        """Human-readable exit summary for ``nns-launch --profile``."""
        st = self.stats()
        lines = [
            f"profile: {st['records']} records "
            f"({st['dropped']} dropped), {st['dispatches']} dispatches, "
            f"sync every {st['sample_every']}",
        ]
        for name in sorted(st["lanes"]):
            lines.append(
                f"  lane {name}: mfu={self._mfu_of(name):.4f} "
                f"roofline={self._roofline_of(name):.3f} "
                f"achieved={self._achieved_of(name):.3e} FLOP/s")
        for s in self.samples()[:10]:
            dev = s["mean_device_us"]
            lines.append(
                f"  {s['label']} {s['shapes']}: n={s['n']} "
                f"host={s['mean_host_us']:.1f}us "
                f"device={f'{dev:.1f}us' if dev is not None else 'n/a'} "
                f"flops={s['flops']:.3g}")
        return "\n".join(lines)


# --------------------------------------------------------------------------- #
# Perfetto / Chrome trace_event export
# --------------------------------------------------------------------------- #

_PID_HOST, _PID_DEVICE, _PID_SERVING, _PID_SCHED, _PID_SLO = 1, 2, 3, 4, 5
_PID_FLEET = 6
_PID_QUALITY = 7


def perfetto_trace(span_store: Optional[_tracing.SpanStore] = None,
                   prof: Optional["Profiler"] = None) -> Dict[str, Any]:
    """Chrome ``trace_event`` JSON (loads in Perfetto / chrome://tracing)
    with three process groups:

      * pid 1 **host** — pipeline.* (and other host) spans, one thread
        lane per pipeline thread; profiler element records fill in when
        tracing is off
      * pid 2 **device** — profiler dispatch records, one lane per
        bundle label (slice duration = synced device time when the
        dispatch carried a probe, else host dispatch time) + kernel
        trace-time instants
      * pid 3 **serving** — serving.* spans in one lane per phase
        (admission_wait / prefill / decode …) + a slot-occupancy
        counter track from engine records
      * pid 4 **sched** — DeviceEngine coalesced-batch slices, one lane
        per work label, plus a coalesce-width / queue-depth counter
        track (multi-tenant multiplexing density at a glance)
      * pid 5 **slo** — one cumulative goodput counter track per tenant
        (met/missed/shed) from obs/slo.py, present when the SLO layer
        is recording
      * pid 6 **fleet** — fleet.* spans (session migrations, one lane
        per operation) from fleet/migrate.py, present when a
        controller has acted
      * pid 7 **quality** — one counter track per data-plane tap
        (mean / PSI drift score / cumulative NaN count) from
        obs/quality, present when quality telemetry is recording

    All timestamps share the process monotonic clock (µs)."""
    store = span_store if span_store is not None else _tracing.store()
    p = prof if prof is not None else _PROFILER
    ev: List[Dict[str, Any]] = []

    def meta(pid: int, tid: int, mname: str, value: str) -> None:
        ev.append({"ph": "M", "name": mname, "pid": pid, "tid": tid,
                   "args": {"name": value}})

    meta(_PID_HOST, 0, "process_name", "host")
    meta(_PID_DEVICE, 0, "process_name", "device")
    meta(_PID_SERVING, 0, "process_name", "serving")
    meta(_PID_SCHED, 0, "process_name", "sched")

    thread_names = {t.ident: t.name for t in threading.enumerate()}
    named_host: set = set()
    serving_rows: Dict[str, int] = {}
    device_rows: Dict[str, int] = {}
    sched_rows: Dict[str, int] = {}
    fleet_rows: Dict[str, int] = {}

    def fleet_row(op: str) -> int:
        row = fleet_rows.get(op)
        if row is None:
            if not fleet_rows:  # lane appears only when fleet acted
                meta(_PID_FLEET, 0, "process_name", "fleet")
            row = fleet_rows[op] = len(fleet_rows) + 1
            meta(_PID_FLEET, row, "thread_name", op)
        return row

    def sched_row(label: str) -> int:
        row = sched_rows.get(label)
        if row is None:
            row = sched_rows[label] = len(sched_rows) + 1
            meta(_PID_SCHED, row, "thread_name", label)
        return row

    def serving_row(phase: str) -> int:
        row = serving_rows.get(phase)
        if row is None:
            row = serving_rows[phase] = len(serving_rows) + 1
            meta(_PID_SERVING, row, "thread_name", phase)
        return row

    def device_row(label: str) -> int:
        row = device_rows.get(label)
        if row is None:
            row = device_rows[label] = len(device_rows) + 1
            meta(_PID_DEVICE, row, "thread_name", label)
        return row

    for s in store.snapshot_spans():
        layer, _, rest = s.name.partition(".")
        if layer == "serving":
            ev.append({
                "name": rest or s.name, "cat": "serving", "ph": "X",
                "ts": s.start_ns / 1e3,
                "dur": max(s.end_ns - s.start_ns, 0) / 1e3,
                "pid": _PID_SERVING, "tid": serving_row(rest or s.name),
                "args": s.attrs,
            })
            continue
        if layer == "fleet":
            ev.append({
                "name": rest or s.name, "cat": "fleet", "ph": "X",
                "ts": s.start_ns / 1e3,
                "dur": max(s.end_ns - s.start_ns, 0) / 1e3,
                "pid": _PID_FLEET, "tid": fleet_row(rest or s.name),
                "args": s.attrs,
            })
            continue
        tid = getattr(s, "tid", 0)
        if tid not in named_host:
            named_host.add(tid)
            meta(_PID_HOST, tid, "thread_name",
                 thread_names.get(tid, f"thread-{tid}"))
        ev.append({
            "name": str(s.attrs.get("element", rest or s.name)),
            "cat": layer, "ph": "X", "ts": s.start_ns / 1e3,
            "dur": max(s.end_ns - s.start_ns, 0) / 1e3,
            "pid": _PID_HOST, "tid": tid, "args": s.attrs,
        })

    for r in p.records():
        kind = r["kind"]
        if kind in ("dispatch", "engine"):
            dur_ns = r["device_ns"] if r["device_ns"] is not None \
                else r["dur_ns"]
            args = dict(r["args"])
            args["clock"] = "device" if r["device_ns"] is not None \
                else "host"
            if r["gap_ns"] is not None:
                args["gap_us"] = r["gap_ns"] / 1e3
            ev.append({
                "name": r["label"], "cat": kind, "ph": "X",
                "ts": r["t0_ns"] / 1e3, "dur": dur_ns / 1e3,
                "pid": _PID_DEVICE, "tid": device_row(r["label"]),
                "args": args,
            })
        elif kind == "kernel":
            ev.append({
                "name": r["label"], "cat": "kernel", "ph": "i", "s": "p",
                "ts": r["t0_ns"] / 1e3, "pid": _PID_DEVICE,
                "tid": device_row(r["label"]), "args": r["args"],
            })
        elif kind == "sched":
            ev.append({
                "name": r["label"], "cat": "sched", "ph": "X",
                "ts": r["t0_ns"] / 1e3, "dur": r["dur_ns"] / 1e3,
                "pid": _PID_SCHED, "tid": sched_row(r["label"]),
                "args": r["args"],
            })
            ev.append({
                "name": f"{r['args']['engine']}.coalesce", "ph": "C",
                "ts": r["t0_ns"] / 1e3, "pid": _PID_SCHED, "tid": 0,
                "args": {"width": r["args"]["width"],
                         "queued": r["args"]["queued"],
                         "inflight": r["args"]["inflight"]},
            })
        elif kind == "occupancy":
            ev.append({
                "name": f"{r['label']}.slots", "ph": "C",
                "ts": r["t0_ns"] / 1e3, "pid": _PID_SERVING, "tid": 0,
                "args": {"active": r["args"]["active"],
                         "queued": r["args"]["queued"]},
            })
        elif kind == "element":
            tid = r["tid"]
            if tid not in named_host:
                named_host.add(tid)
                meta(_PID_HOST, tid, "thread_name",
                     thread_names.get(tid, f"thread-{tid}"))
            ev.append({
                "name": r["label"], "cat": "element", "ph": "X",
                "ts": r["t0_ns"] / 1e3, "dur": r["dur_ns"] / 1e3,
                "pid": _PID_HOST, "tid": tid, "args": r["args"],
            })

    slo_points = _slo.trace_points()
    if slo_points:
        meta(_PID_SLO, 0, "process_name", "slo")
        for pt in slo_points:
            ev.append({
                "name": f"{pt['tenant']}.goodput", "ph": "C",
                "ts": pt["t_ns"] / 1e3, "pid": _PID_SLO, "tid": 0,
                "args": {"met": pt["met"], "missed": pt["missed"],
                         "shed": pt["shed"]},
            })

    q_points = _quality.trace_points()
    if q_points:
        meta(_PID_QUALITY, 0, "process_name", "quality")
        for pt in q_points:
            ev.append({
                "name": f"{pt['tap']}.quality", "ph": "C",
                "ts": pt["t_ns"] / 1e3, "pid": _PID_QUALITY, "tid": 0,
                "args": {"mean": pt["mean"], "psi": pt["psi"],
                         "nan": pt["nan"]},
            })

    return {
        "traceEvents": ev,
        "displayTimeUnit": "ms",
        "otherData": {
            "profile_enabled": p.is_enabled,
            "tracing_enabled": store.is_enabled,
            "slo_enabled": _slo.enabled(),
            "quality_enabled": _quality.enabled(),
            **p.stats(),
        },
    }


# --------------------------------------------------------------------------- #
# Process-global profiler + hook install
# --------------------------------------------------------------------------- #

_PROFILER = Profiler(enabled=False)


def profiler() -> Profiler:
    return _PROFILER


def enabled() -> bool:
    return _PROFILER._enabled


def enable(max_records: Optional[int] = None,
           sample_every: Optional[int] = None) -> None:
    """Turn profiling on: register metric families and install every
    hook. ``max_records`` resizes the ring (``--profile=N``);
    ``sample_every`` sets the device-sync probe cadence."""
    global DISPATCH_HOOK, ENGINE_HOOK, KERNEL_HOOK, SCHED_HOOK
    p = _PROFILER
    if max_records is not None:
        p.resize(max_records)
    if sample_every is not None:
        p.sample_every = max(1, int(sample_every))
    p._enabled = True
    p._register_metrics()
    DISPATCH_HOOK = p
    ENGINE_HOOK = p
    KERNEL_HOOK = p.record_kernel
    SCHED_HOOK = p
    try:
        from ..graph import element as _gel
        _gel.PROFILE_CHAIN_HOOK = p.profiled_chain
    except ImportError:  # mid-import of graph: pipeline hooks come later
        pass
    try:
        from ..ops import epilogue as _epi
        _epi.EPILOGUE_SELECT_HOOK = p.epilogue_select
    except ImportError:
        pass
    _events.record("profile.capture_start",
                   f"profiling on (ring={p._records.maxlen}, "
                   f"sync every {p.sample_every})")


def disable() -> None:
    """Turn profiling off and clear every hook — hot paths are back to
    one None check. Recorded data stays readable until reset()."""
    global DISPATCH_HOOK, ENGINE_HOOK, KERNEL_HOOK, SCHED_HOOK
    p = _PROFILER
    if p._enabled:
        _events.record("profile.capture_stop",
                       f"profiling off ({len(p._records)} records held)")
    p._enabled = False
    DISPATCH_HOOK = None
    ENGINE_HOOK = None
    KERNEL_HOOK = None
    SCHED_HOOK = None
    try:
        from ..graph import element as _gel
        _gel.PROFILE_CHAIN_HOOK = None
    except ImportError:
        pass
    try:
        from ..ops import epilogue as _epi
        _epi.EPILOGUE_SELECT_HOOK = None
    except ImportError:
        pass


def samples() -> List[Dict[str, Any]]:
    return _PROFILER.samples()


def dump_samples(path: str) -> int:
    return _PROFILER.dump_samples(path)


def report() -> str:
    return _PROFILER.report()


if os.environ.get("NNSTPU_PROFILE", "") == "1":
    enable()
