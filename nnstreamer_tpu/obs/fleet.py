"""obs.fleet — cross-process observability for a multi-host deployment.

PR 1–3 built metrics, tracing, and health as strictly single-process
subsystems: a client pipeline offloading to a remote ``tensor_query``
server sees only its own half of every request, and a TPU pod serving
fleet would need one scrape target per process. This module makes the
subsystem pod-shaped — **one scrape endpoint, one trace tree, one
health verdict**:

  * **Metric federation.** Workers periodically push compact registry
    snapshots (plus health and exported spans) to an *aggregator*,
    which re-exposes every instance's series on its ``/metrics`` with
    ``instance``/``role`` labels appended. Counters and histograms are
    cumulative per instance, so merging is last-snapshot-wins per
    instance; ``# HELP``/``# TYPE`` are emitted exactly once per
    family however many instances report it, and a family whose type
    disagrees across instances is skipped with a
    ``fleet.merge_conflict`` event instead of corrupting the scrape.
  * **Remote span collection.** Workers export completed spans of
    traces whose ids crossed the query wire (marked at wire
    send/adopt time — obs/tracing.py ``mark_export``); the aggregator
    ingests them into its span store, so ``/debug/traces/<id>``
    renders the full cross-host tree stitched by the propagated trace
    id.
  * **Fleet health rollup.** Each push carries the worker's health
    snapshot and readiness verdict. The aggregator's ``/healthz`` /
    ``/readyz`` / ``/debug/fleet`` report worst-of-fleet status with
    per-instance detail; a missing push heartbeat flips the instance
    ``stalled`` (kind="fleet" watchdog rule, obs/health.py) and a
    long-gone instance expires entirely (``fleet.expire``).

Transport is dual: an ``OBS_PUSH`` frame piggybacked on an open
``tensor_query`` connection (the client sends one ahead of a DATA
frame when the push interval has elapsed — no extra socket, no extra
thread), and a standalone HTTP ``POST /fleet/push`` to the
aggregator's exporter for processes that have no query wire (a
serving-only host, the CLI ``--obs-push URL`` path).

Zero-overhead contract, same as the rest of obs: with fleet push
disabled there are **no extra wire bytes** (``wire_frame_due`` is a
module-global None check; no ``OBS_PUSH`` frame is ever built), **no
background threads** (the HTTP pusher thread only exists while a URL
push is enabled), and span export costs one attribute read in the
span store. Stdlib only.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from . import events as _events
from . import health as _health
from . import metrics as _metrics
from . import quality as _quality
from . import slo as _slo
from . import tracing as _tracing
from .metrics import _escape_help, _escape_label, _fmt

__all__ = [
    "FleetAggregator", "FleetPusher", "PUSH_VERSION", "aggregator",
    "build_push", "default_instance", "disable_aggregator",
    "disable_push", "enable_aggregator", "enable_push", "ingest_wire",
    "push_enabled", "pusher", "wire_frame_due",
]

#: push document schema version (bump on incompatible change; the
#: aggregator rejects unknown majors with a clear error)
PUSH_VERSION = 1

#: default seconds between pushes (CLI/API override)
DEFAULT_INTERVAL_S = 2.0

#: staleness: an instance whose last push is older than
#: ``ttl_factor * its advertised interval`` is stale (not-ready +
#: watchdog ``stalled``); older than ``expire_factor * interval`` it
#: is dropped from the fleet entirely
TTL_FACTOR = 3.0
EXPIRE_FACTOR = 15.0

#: bounded count of expired-instance tombstones kept for routing views
TOMBSTONE_LIMIT = 64

#: gauge families whose series sum to an instance's routing queue
#: depth (serving admission queue, query inbox, pipeline queues)
QUEUE_DEPTH_FAMILIES = ("nnstpu_serving_queue_depth",
                        "nnstpu_query_inbox_depth",
                        "nnstpu_pipeline_queue_depth")

#: per-push span batch bound (the store-side queue is bounded too)
MAX_SPANS_PER_PUSH = 512

#: HTTP ingestion body cap — a push is a snapshot, not a bulk upload
MAX_PUSH_BYTES = 8 << 20

#: digest entries per push — bounds both doc size and the router's
#: probe cost; deep trees advertise their first 64 BFS nodes, which
#: covers the hot shared prefixes placement actually cares about
MAX_KV_PREFIX_ENTRIES = 64

#: serving/disagg.py installs a zero-arg callable returning the local
#: engine's bounded radix-prefix digest (kv_cache.prefix_digest());
#: None (the default) keeps the push doc exactly as it was — the
#: usual zero-overhead-when-off hook (slo.ENGINE_SLO_HOOK pattern)
KV_DIGEST_HOOK = None

#: tune/ installs a zero-arg callable returning the local autotuner
#: store's push slice (tune.TuneStore.to_doc()); None keeps the push
#: doc exactly as before — same contract as KV_DIGEST_HOOK
TUNE_PUSH_HOOK = None

#: tune/ installs a one-arg callable that merges a fleet-shipped tune
#: doc into the local store. The pusher fires it with the ``tune``
#: field of every push-ack (see FleetPusher.push_now) — the adoption
#: path that lets a fresh instance skip sweeps the fleet already paid
#: for. None-gated like every other hook here.
TUNE_ADOPT_HOOK = None

#: fleet/ installs a zero-arg callable returning the local
#: FleetController's bounded action journal (controller.actions()) so
#: scale/migration decisions federate through push docs like every
#: other telemetry slice. None-gated like the hooks above; assigned
#: only by fleet.enable()/disable() (nnslint ownership rule).
FLEET_ACTIONS_HOOK = None

#: obs/diag installs a zero-arg callable returning the local debug-
#: bundle references + trigger accounting (DiagEngine.push_doc) so an
#: aggregator can enumerate the whole fleet's captured evidence for
#: one incident. None keeps the push doc exactly as before; assigned
#: only by obs/diag enable()/disable() (nnslint diag ownership rule).
DIAG_PUSH_HOOK = None

#: fleet/checkpoint.py installs a zero-arg callable returning the
#: local CheckpointDaemon's session → last-checkpointed-seq watermarks
#: (daemon.watermarks()). They ride every push doc so that when this
#: instance dies WITHOUT a drain, its tombstone still says which
#: checkpoints must exist somewhere — the staleness bar the restore
#: path holds survivors' blobs to. None-gated like every hook here;
#: assigned only by fleet/checkpoint.py (nnslint checkpoint rule).
CHECKPOINT_HOOK = None

#: checkpoint watermark entries per push/tombstone — bounds both the
#: doc and what a tombstone pins in memory awaiting restore
MAX_CHECKPOINT_SESSIONS = 256

#: tombstones still carrying unconsumed checkpoint watermarks are
#: protected from compaction for this long after expiry (the restore
#: window), and at most this many are protected at once — past either
#: bound they compact like any other stone (the bounded-window fix)
RESTORE_WINDOW_S = 60.0
RESTORE_PROTECT_LIMIT = 16


def default_instance() -> str:
    """``host:pid`` unless ``NNSTPU_INSTANCE`` names the process —
    unique per process on a pod without any coordination."""
    return os.environ.get("NNSTPU_INSTANCE") \
        or f"{socket.gethostname()}:{os.getpid()}"


def build_push(instance: str, role: str, seq: int,
               interval_s: float = DEFAULT_INTERVAL_S,
               registry: Optional[_metrics.MetricsRegistry] = None,
               health_registry: Optional[_health.HealthRegistry] = None,
               span_store: Optional[_tracing.SpanStore] = None,
               max_spans: int = MAX_SPANS_PER_PUSH,
               kv_prefix: Optional[List[str]] = None,
               checkpoints: Optional[Dict[str, int]] = None,
               endpoint: Optional[str] = None) -> Dict[str, Any]:
    """Assemble one push document from the given (default: process-
    global) registries — the single source of truth for the push
    schema, shared by the pusher, the wire piggyback, and tests."""
    reg = registry if registry is not None else _metrics.registry()
    hreg = health_registry if health_registry is not None \
        else _health.registry()
    store = span_store if span_store is not None else _tracing.store()
    ready, conds = hreg.readiness()
    if kv_prefix is None and KV_DIGEST_HOOK is not None:
        kv_prefix = KV_DIGEST_HOOK()
    if checkpoints is None and CHECKPOINT_HOOK is not None:
        checkpoints = CHECKPOINT_HOOK()
    return {
        "v": PUSH_VERSION,
        "instance": instance,
        "role": role,
        "seq": int(seq),
        "ts": time.time(),
        "interval_s": float(interval_s),
        "metrics": reg.snapshot(),
        "health": hreg.snapshot(),
        "ready": {"ready": ready, "conditions": conds},
        "spans": store.drain_export(max_spans),
        # None while the SLO layer is off — a worker without per-tenant
        # accounting pushes the same doc it always did
        "slo": _slo.push_data(),
        # None while no digest source is registered (same contract as
        # slo): the bounded radix-prefix digest the router probes for
        # prefix-cache-aware placement, capped at MAX_KV_PREFIX_ENTRIES
        "kv_prefix": (None if kv_prefix is None
                      else [str(h) for h in kv_prefix]
                      [:MAX_KV_PREFIX_ENTRIES]),
        # None while the autotuner is off (same contract again): the
        # local store's tuned-config slice, federated so any instance's
        # sweep result reaches the whole fleet
        "tune": TUNE_PUSH_HOOK() if TUNE_PUSH_HOOK is not None else None,
        # None while no controller runs here (same contract): the
        # bounded autoscale action journal, so any aggregator can
        # answer "who scaled what, when, and why"
        "fleet_actions": (FLEET_ACTIONS_HOOK()
                          if FLEET_ACTIONS_HOOK is not None else None),
        # None while diag is off (same contract): bundle references +
        # trigger accounting, so the aggregator enumerates fleet-wide
        # incident evidence without shipping the bundles themselves
        "diag": DIAG_PUSH_HOOK() if DIAG_PUSH_HOOK is not None else None,
        # None while data-plane quality is off (same contract): the
        # per-tap frame/NaN/PSI summary + anomaly verdicts, small
        # enough to ride every push so an aggregator can answer
        # "which instance's which tap is producing garbage"
        "quality": _quality.push_data(),
        # None while no checkpoint daemon runs here (same contract):
        # session → last-checkpointed seq, bounded — the slice a
        # tombstone keeps so a crash restore knows what freshness to
        # demand of survivors' shelved blobs
        "checkpoints": (None if checkpoints is None else
                        {str(s): int(q) for s, q in
                         sorted(checkpoints.items())
                         [:MAX_CHECKPOINT_SESSIONS]}),
        # None unless the worker serves a wire endpoint: how the fleet
        # controller maps a tombstoned instance back to the router
        # backend whose sessions need re-homing
        "endpoint": None if endpoint is None else str(endpoint),
    }


# --------------------------------------------------------------------------- #
# Pusher (worker side)
# --------------------------------------------------------------------------- #

class FleetPusher:
    """Ships this process's snapshots to an aggregator.

    ``url`` (``http://host:port`` or a bare ``host:port``) starts a
    daemon thread POSTing to ``/fleet/push`` every ``interval_s``;
    ``url=None`` is wire-only mode — no thread, pushes ride the query
    wire via :meth:`wire_frame` whenever the client sends anyway.
    Both modes share one interval clock per channel, and both flip
    span export on in the span store so wire-crossing traces queue
    their spans for the next push.
    """

    def __init__(self, url: Optional[str] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 instance: Optional[str] = None, role: str = "worker",
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 health_registry: Optional[_health.HealthRegistry] = None,
                 span_store: Optional[_tracing.SpanStore] = None,
                 kv_digest: Optional[Any] = None):
        self.instance = instance or default_instance()
        self.role = role
        # per-pusher digest source; None defers to the module-level
        # KV_DIGEST_HOOK inside build_push (serving/disagg.py installs
        # that hook when a worker starts, so a plain FleetPusher next to
        # a DisaggWorker advertises the digest with no extra wiring)
        self._kv_digest = kv_digest
        self.interval_s = max(float(interval_s), 0.05)
        self._registry = registry
        self._health_registry = health_registry
        self._store = span_store if span_store is not None \
            else _tracing.store()
        self._host, self._port = self._parse_url(url)
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._last_wire = 0.0
        self._http_failing = False
        self.pushes_sent = 0
        self.push_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._store.set_export(True)
        if self._host is not None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"obs-fleet-push:{self.instance}")
            self._thread.start()

    @staticmethod
    def _parse_url(url: Optional[str]) -> Tuple[Optional[str], int]:
        if not url:
            return None, 0
        if "//" not in url:
            url = "http://" + url
        parts = urlsplit(url)
        if parts.scheme != "http" or not parts.hostname:
            raise ValueError(
                f"fleet push URL must be http://host:port, got {url!r}")
        return parts.hostname, parts.port or 9464

    def _next_doc(self) -> Dict[str, Any]:
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        return build_push(self.instance, self.role, seq,
                          interval_s=self.interval_s,
                          registry=self._registry,
                          health_registry=self._health_registry,
                          span_store=self._store,
                          kv_prefix=(self._kv_digest()
                                     if self._kv_digest is not None
                                     else None))

    # -- HTTP channel --------------------------------------------------- #
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.push_now()

    def push_now(self) -> bool:
        """One synchronous HTTP push (the thread's tick; callable
        directly for deterministic tests). Failures are counted and
        journaled on state *change* only — a down aggregator must not
        flood the event ring at push rate."""
        if self._host is None:
            return False
        doc = self._next_doc()
        body = json.dumps(doc, default=str).encode("utf-8")
        try:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=5.0)
            try:
                conn.request("POST", "/fleet/push", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                ack = resp.read()
                if resp.status != 200:
                    raise OSError(f"aggregator replied {resp.status}")
            finally:
                conn.close()
            # the ack carries the fleet's merged tuned configs (obs/
            # exporter.py _post_fleet_push): adopt them when the
            # autotuner is on. First-push adoption is what lets a fresh
            # instance skip sweeps the fleet already paid for — enable
            # fleet push before the first dispatch and the configs are
            # local before any knob is consulted.
            hook = TUNE_ADOPT_HOOK
            if hook is not None and ack:
                try:
                    tdoc = json.loads(ack).get("tune")
                    if tdoc is not None:
                        hook(tdoc)
                except (ValueError, AttributeError):
                    pass  # pre-tune aggregator or non-JSON ack
        except (OSError, http.client.HTTPException) as e:
            # the doc drained the span export queue — put the batch
            # back so a briefly unreachable aggregator loses nothing
            self._store.requeue_export(doc.get("spans") or [])
            self.push_errors += 1
            if not self._http_failing:
                self._http_failing = True
                _events.record(
                    "fleet.push_failed",
                    f"{self.instance}: push to {self._host}:{self._port} "
                    f"failed: {e}", severity="warning",
                    instance=self.instance)
            return False
        self.pushes_sent += 1
        if self._http_failing:
            self._http_failing = False
            _events.record("fleet.push_recovered",
                           f"{self.instance}: pushes reaching "
                           f"{self._host}:{self._port} again",
                           instance=self.instance)
        return True

    # -- query-wire channel --------------------------------------------- #
    def wire_frame(self) -> Optional[Tuple[Dict[str, Any], bytes]]:
        """(meta, payload) for one ``OBS_PUSH`` frame when the wire
        interval has elapsed, else None. Called by the query client
        immediately before a DATA send — same thread, same socket, so
        the push never races a request frame. The interval gate is a
        locked check-then-set: two query-client elements sharing the
        process-global pusher must not both emit a frame in one
        interval."""
        now = time.monotonic()
        with self._seq_lock:
            if now - self._last_wire < self.interval_s:
                return None
            self._last_wire = now
        doc = self._next_doc()
        meta = {"instance": doc["instance"], "role": doc["role"],
                "seq": doc["seq"], "v": doc["v"]}
        return meta, json.dumps(doc, default=str).encode("utf-8")

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._thread = None
        # Final flush: a worker that lived shorter than one interval
        # would otherwise exit without ever reporting. Best-effort —
        # push_now() swallows a down aggregator.
        if self._host is not None:
            self.push_now()
        self._store.set_export(False)


# --------------------------------------------------------------------------- #
# Aggregator
# --------------------------------------------------------------------------- #

class _Instance:
    """Latest state pushed by one worker process."""

    __slots__ = ("instance", "role", "seq", "ts", "interval_s",
                 "metrics", "health", "ready", "slo", "kv_prefix",
                 "tune", "actions", "diag", "quality", "checkpoints",
                 "endpoint", "via", "pushes",
                 "spans_ingested", "first_mono", "last_mono")

    def __init__(self, instance: str):
        self.instance = instance
        self.role = "worker"
        self.seq = 0
        self.ts = 0.0
        self.interval_s = DEFAULT_INTERVAL_S
        self.metrics: Dict[str, Any] = {}
        self.health: Dict[str, Any] = {}
        self.ready: Dict[str, Any] = {"ready": False, "conditions": {}}
        self.slo: Optional[Dict[str, Any]] = None
        #: frozenset of radix path hashes (None until the instance
        #: first advertises one) — set membership IS the prefix probe:
        #: chained hashes mean hashes[i] present implies path 0..i held
        self.kv_prefix: Optional[frozenset] = None
        #: the instance's tune-store slice (None until it pushes one)
        self.tune: Optional[Dict[str, Any]] = None
        #: the instance's autoscale action journal (None until a
        #: controller there pushes one)
        self.actions: Optional[List[Dict[str, Any]]] = None
        #: the instance's diag slice: debug-bundle references +
        #: trigger accounting (None until diag pushes one)
        self.diag: Optional[Dict[str, Any]] = None
        #: the instance's data-plane quality slice: per-tap frame/NaN/
        #: PSI summary + anomaly verdicts (None until quality pushes)
        self.quality: Optional[Dict[str, Any]] = None
        #: the instance's checkpoint watermarks, session → seq (None
        #: until a checkpoint daemon there pushes them) — copied into
        #: the tombstone on expiry so the restore path outlives the
        #: worker
        self.checkpoints: Optional[Dict[str, int]] = None
        #: the instance's wire endpoint (None until advertised) — the
        #: router-backend join key a restore needs
        self.endpoint: Optional[str] = None
        self.via = "http"
        self.pushes = 0
        self.spans_ingested = 0
        self.first_mono = time.monotonic()
        self.last_mono = self.first_mono


class FleetAggregator:
    """Holds the fleet state and renders the merged views.

    ``ttl_s``/``expire_after_s`` override the per-instance defaults
    (``TTL_FACTOR`` / ``EXPIRE_FACTOR`` × the instance's advertised
    push interval). Expiry runs lazily on every ingest and read — no
    thread of its own; the health watchdog (when enabled) additionally
    drives the ``stalled`` verdict between reads.
    """

    def __init__(self, ttl_s: Optional[float] = None,
                 expire_after_s: Optional[float] = None,
                 span_store: Optional[_tracing.SpanStore] = None,
                 instance: Optional[str] = None, role: str = "aggregator"):
        self.ttl_s = ttl_s
        self.expire_after_s = expire_after_s
        self.instance = instance or default_instance()
        self.role = role
        self._store = span_store if span_store is not None \
            else _tracing.store()
        self._lock = threading.Lock()
        self._instances: "OrderedDict[str, _Instance]" = OrderedDict()
        #: expired instances, kept (bounded) so routing views report
        #: them as not-routable instead of silently dropping the key;
        #: a fresh push from the same instance clears its tombstone
        self._tombstones: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        #: (instance, family) pairs already journaled as conflicts —
        #: one event per drift, not one per scrape
        self._conflicts: set = set()
        self.pushes_ingested = 0
        self.bad_pushes = 0

    # -- staleness ------------------------------------------------------- #
    def _ttl(self, rec: _Instance) -> float:
        if self.ttl_s is not None:
            return float(self.ttl_s)
        return max(TTL_FACTOR * rec.interval_s, 0.5)

    def _expire_after(self, rec: _Instance) -> float:
        if self.expire_after_s is not None:
            return float(self.expire_after_s)
        return max(EXPIRE_FACTOR * rec.interval_s, 2.0)

    def _expire_now(self) -> None:
        now = time.monotonic()
        dead: List[_Instance] = []
        with self._lock:
            for iid in list(self._instances):
                rec = self._instances[iid]
                if now - rec.last_mono > self._expire_after(rec):
                    dead.append(self._instances.pop(iid))
                    # expiry leaves a tombstone, not silence: a router
                    # asking about this instance must see "known dead"
                    # (routable=False), not an absent key it could
                    # misread as "never part of the fleet"
                    stone: Dict[str, Any] = {
                        "role": rec.role, "expired_mono": now}
                    # carry the last pushed checkpoint watermarks +
                    # endpoint into the stone: the worker is gone, so
                    # this copy is all a crash restore has to judge
                    # survivors' blobs by (bounded at ingest)
                    if rec.endpoint:
                        stone["endpoint"] = rec.endpoint
                    if rec.checkpoints is not None:
                        stone["checkpoints"] = dict(rec.checkpoints)
                    self._tombstones[iid] = stone
                    self._tombstones.move_to_end(iid)
            self._compact_tombstones()
        for rec in dead:
            _events.record(
                "fleet.expire",
                f"instance {rec.instance} expired after "
                f"{now - rec.last_mono:.1f}s without a push",
                severity="warning", instance=rec.instance, role=rec.role)

    def _compact_tombstones(self) -> None:  # guarded-by: _lock
        """Deterministic oldest-first compaction: when churn pushes the
        tombstone census past the bound, evict the stones that expired
        EARLIEST (by expiry time, tiebroken by instance id) — never
        whichever insertion order a re-expiry happened to leave. The
        newest deaths are the ones a router still needs to learn.

        Stones still carrying unconsumed checkpoint watermarks are
        skipped while inside the RESTORE_WINDOW_S grace (a restore
        that hasn't run yet must still find them), but the protection
        is bounded twice over: the grace expires, and at most
        RESTORE_PROTECT_LIMIT stones enjoy it at once — the OLDEST
        protected stones lose it first when crash churn exceeds the
        bound, so compaction always terminates."""
        now = time.monotonic()

        def protected(stone: Dict[str, Any]) -> bool:
            return ("checkpoints" in stone
                    and now - float(stone.get("expired_mono", 0.0))
                    <= RESTORE_WINDOW_S)

        guard = sorted(
            (kv for kv in self._tombstones.items() if protected(kv[1])),
            key=lambda kv: (-float(kv[1].get("expired_mono", 0.0)),
                            kv[0]))
        immune = {iid for iid, _ in guard[:RESTORE_PROTECT_LIMIT]}
        while len(self._tombstones) > TOMBSTONE_LIMIT:
            evictable = [kv for kv in self._tombstones.items()
                         if kv[0] not in immune]
            if not evictable:
                break  # every stone is inside the bounded window
            oldest = min(
                evictable,
                key=lambda kv: (float(kv[1].get("expired_mono", 0.0)),
                                kv[0]))[0]
            del self._tombstones[oldest]

    def confirm_drain(self, iid: str) -> bool:
        """Controller-confirmed drain (fleet/controller.py): the
        instance was deliberately scaled in and its sessions migrated,
        so drop both its live record and any tombstone — deliberate
        autoscale churn must never crowd still-dead backends out of
        the bounded tombstone list. Returns whether anything cleared."""
        with self._lock:
            had_rec = self._instances.pop(iid, None) is not None
            had_stone = self._tombstones.pop(iid, None) is not None
        cleared = had_rec or had_stone
        if cleared:
            _events.record(
                "fleet.drain_confirmed",
                f"instance {iid} drained by controller — record and "
                f"tombstone cleared", instance=iid)
        return cleared

    def restorables(self) -> List[Dict[str, Any]]:
        """Tombstoned instances a crash restore should handle: died
        without a drain, advertised a wire endpoint, and their
        checkpoint watermarks are still unconsumed. Sorted oldest
        death first — the controller works the backlog in the order
        the fleet lost them."""
        self._expire_now()
        with self._lock:
            rows = [
                {"instance": iid,
                 "endpoint": stone["endpoint"],
                 "checkpoints": dict(stone.get("checkpoints") or {}),
                 "expired_mono": float(stone.get("expired_mono", 0.0))}
                for iid, stone in self._tombstones.items()
                if stone.get("endpoint")
                and not stone.get("restore_consumed")]
        return sorted(rows, key=lambda r: (r["expired_mono"],
                                           r["instance"]))

    def consume_restore(self, iid: str) -> Optional[Dict[str, Any]]:
        """Atomically claim a tombstone's restore payload (endpoint +
        checkpoint watermarks). First caller wins — a second restore
        attempt gets None instead of splicing the same sessions twice.
        The stone itself stays for the routing view until
        ``confirm_drain`` clears it, but once consumed it loses its
        compaction protection (the window closes on consumption, not
        just on time)."""
        with self._lock:
            stone = self._tombstones.get(iid)
            if stone is None or stone.get("restore_consumed") \
                    or not stone.get("endpoint"):
                return None
            stone["restore_consumed"] = True
            payload = {"instance": iid,
                       "endpoint": stone["endpoint"],
                       "checkpoints": dict(
                           stone.pop("checkpoints", None) or {})}
        return payload

    # -- ingestion ------------------------------------------------------- #
    def ingest(self, doc: Any, via: str = "http") -> None:
        """Validate and store one push document; raises ValueError on a
        malformed document (the HTTP route maps that to 400)."""
        if not isinstance(doc, dict):
            self.bad_pushes += 1
            raise ValueError("push document must be a JSON object")
        iid = doc.get("instance")
        if not isinstance(iid, str) or not iid:
            self.bad_pushes += 1
            raise ValueError("push document missing 'instance'")
        v = doc.get("v", 0)
        if not isinstance(v, int) or v > PUSH_VERSION:
            self.bad_pushes += 1
            raise ValueError(
                f"unsupported push version {v!r} (this aggregator "
                f"speaks v<={PUSH_VERSION})")
        # Coerce every scalar into locals BEFORE touching the fleet
        # table: a push that fails validation must leave no ghost
        # half-mutated instance behind (one bad push would otherwise
        # flip /readyz 503 fleet-wide until expiry), and non-scalar
        # junk (e.g. "seq": [1]) must surface as the ValueError the
        # HTTP route and wire handler are contracted to catch.
        try:
            role = str(doc.get("role")) if doc.get("role") else None
            seq = int(doc.get("seq") or 0)
            ts = float(doc.get("ts") or 0.0)
            interval_s = max(
                float(doc.get("interval_s") or DEFAULT_INTERVAL_S), 0.05)
        except (TypeError, ValueError) as e:
            self.bad_pushes += 1
            raise ValueError(
                f"malformed push field from {iid}: {e}") from e
        spans = doc.get("spans") or []
        metrics = doc.get("metrics")
        health = doc.get("health")
        ready = doc.get("ready")
        slo_doc = doc.get("slo")
        kv_prefix = doc.get("kv_prefix")
        tune_doc = doc.get("tune")
        actions_doc = doc.get("fleet_actions")
        diag_doc = doc.get("diag")
        quality_doc = doc.get("quality")
        ckpt_doc = doc.get("checkpoints")
        endpoint_doc = doc.get("endpoint")
        new = False
        with self._lock:
            rec = self._instances.get(iid)
            if rec is None:
                rec = _Instance(iid)
                self._instances[iid] = rec
                new = True
            if role:
                rec.role = role
            rec.seq = seq
            rec.ts = ts
            rec.interval_s = interval_s
            if isinstance(metrics, dict):
                rec.metrics = metrics
            if isinstance(health, dict):
                rec.health = health
            if isinstance(ready, dict):
                rec.ready = ready
            if isinstance(slo_doc, dict):
                rec.slo = slo_doc
            if isinstance(kv_prefix, (list, tuple)):
                # replace, never merge: the digest is a snapshot of
                # what the instance holds NOW — evicted paths must
                # stop attracting placements
                rec.kv_prefix = frozenset(
                    str(h) for h in kv_prefix[:MAX_KV_PREFIX_ENTRIES])
            if isinstance(tune_doc, dict):
                rec.tune = tune_doc
            if isinstance(actions_doc, list):
                rec.actions = actions_doc
            if isinstance(diag_doc, dict):
                rec.diag = diag_doc
            if isinstance(quality_doc, dict):
                rec.quality = quality_doc
            if isinstance(ckpt_doc, dict):
                # replace, never merge — the watermarks are a snapshot
                # of what the daemon has stored NOW; junk values drop
                # per-entry rather than poisoning the slice
                marks: Dict[str, int] = {}
                for s, q in list(ckpt_doc.items())[
                        :MAX_CHECKPOINT_SESSIONS]:
                    try:
                        marks[str(s)] = int(q)
                    except (TypeError, ValueError):
                        continue
                rec.checkpoints = marks
            if isinstance(endpoint_doc, str) and endpoint_doc:
                rec.endpoint = endpoint_doc
            rec.via = via
            rec.pushes += 1
            rec.last_mono = time.monotonic()
            self.pushes_ingested += 1
            # a returning instance is alive again: drop its tombstone
            self._tombstones.pop(iid, None)
        if isinstance(spans, list) and spans:
            ingested = self._store.ingest_remote(spans, iid)
            with self._lock:
                rec.spans_ingested += ingested
        if new:
            self._register_health(iid)
        _events.record(
            "fleet.push",
            f"push from {iid} (seq {rec.seq}, via {via}, "
            f"{len(spans)} span(s))",
            severity="debug", instance=iid, role=rec.role, seq=rec.seq,
            via=via)
        self._expire_now()

    def _register_health(self, iid: str) -> None:
        """One kind="fleet" component per instance: the watchdog's
        missing-heartbeat rule reads the probe's push age; an expired
        instance retires the component (probe → None). A no-op while
        health is off."""
        ref = weakref.ref(self)

        def probe() -> Optional[Dict[str, Any]]:
            agg = ref()
            if agg is None:
                return None
            with agg._lock:
                rec = agg._instances.get(iid)
                if rec is None:
                    return None
                return {
                    "push_age_s": time.monotonic() - rec.last_mono,
                    "ttl_s": agg._ttl(rec),
                    "pushes": rec.pushes,
                    "role": rec.role,
                }

        _health.component(f"fleet:{iid}", kind="fleet", probe=probe,
                          attrs={"instance": iid})

    # -- merged exposition ------------------------------------------------ #
    def exposition(self, local_registry: Optional[_metrics.MetricsRegistry]
                   = None) -> str:
        """Prometheus text for the whole fleet: the local registry's
        series plus every live instance's pushed snapshot, each series
        tagged with ``instance``/``role``. HELP/TYPE exactly once per
        family; a family whose type conflicts with the first-seen
        schema is skipped per offending instance (``fleet.merge_
        conflict`` journaled once)."""
        self._expire_now()
        reg = local_registry if local_registry is not None \
            else _metrics.registry()
        sources: List[Tuple[str, str, Dict[str, Any]]] = [
            (self.instance, self.role, reg.snapshot())]
        with self._lock:
            for rec in self._instances.values():
                sources.append((rec.instance, rec.role, rec.metrics))
        conflicts: List[Tuple[str, str, str, str]] = []
        fams: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        for iid, role, snap in sources:
            for name in sorted(snap):
                fam = snap[name]
                ftype = fam.get("type", "")
                cur = fams.get(name)
                if cur is None:
                    cur = {"type": ftype, "help": fam.get("help", ""),
                           "rows": []}
                    fams[name] = cur
                elif cur["type"] != ftype:
                    key = (iid, name)
                    with self._lock:
                        fresh = key not in self._conflicts
                        if fresh:
                            self._conflicts.add(key)
                    if fresh:
                        conflicts.append((iid, name, ftype, cur["type"]))
                    continue
                for series in fam.get("series", []):
                    labels = dict(series.get("labels") or {})
                    labels["instance"] = iid
                    labels["role"] = role
                    cur["rows"].append((labels, series))
        for iid, name, ftype, want in conflicts:
            _events.record(
                "fleet.merge_conflict",
                f"{iid}: family {name} pushed as {ftype!r}, fleet has "
                f"{want!r} — skipped", severity="warning", instance=iid,
                family=name)
        lines: List[str] = []
        for name in sorted(fams):
            fam = fams[name]
            if not fam["rows"]:
                continue
            if fam["help"]:
                lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for labels, series in fam["rows"]:
                base = ",".join(
                    f'{k}="{_escape_label(str(v))}"'
                    for k, v in labels.items())
                if fam["type"] == "histogram":
                    # snapshot buckets are already cumulative
                    buckets = series.get("buckets") or {}
                    for bound in sorted(buckets, key=float):
                        le = f'le="{_fmt(float(bound))}"'
                        lines.append(
                            f"{name}_bucket{{{base},{le}}} "
                            f"{buckets[bound]}")
                    count = series.get("count", 0)
                    lines.append(
                        f'{name}_bucket{{{base},le="+Inf"}} {count}')
                    lines.append(f"{name}_sum{{{base}}} "
                                 f"{_fmt(float(series.get('sum', 0.0)))}")
                    lines.append(f"{name}_count{{{base}}} {count}")
                else:
                    lines.append(
                        f"{name}{{{base}}} "
                        f"{_fmt(float(series.get('value', 0.0)))}")
        return "\n".join(lines) + "\n" if lines else ""

    # -- health / readiness rollup ---------------------------------------- #
    def health_rollup(self, local: Dict[str, Any]) -> Dict[str, Any]:
        """Worst-of-fleet /healthz body: the local snapshot's components
        plus one ``fleet:<instance>`` entry per live instance carrying
        its pushed status (stale push ⇒ ``stalled`` regardless of what
        it last claimed). The kind="fleet" components _register_health
        put in the *local* registry (for the watchdog's heartbeat rule)
        are dropped here — this rollup is the authoritative per-instance
        view, and keeping both would list every instance twice with
        potentially conflicting statuses."""
        self._expire_now()
        now = time.monotonic()
        components = [c for c in local.get("components", [])
                      if c.get("kind") != "fleet"]
        # re-derive the local verdict from the surviving components so a
        # watchdog-stalled fleet:<iid> duplicate can't leak its status in
        worst = _health.Status.OK
        for c in components:
            s = _health.status_from_string(str(c.get("status", "ok")))
            if s > worst:
                worst = s
        with self._lock:
            recs = list(self._instances.values())
        for rec in recs:
            age = now - rec.last_mono
            stale = age > self._ttl(rec)
            st = "stalled" if stale \
                else str(rec.health.get("status", "ok"))
            s = _health.status_from_string(st)
            if s > worst:
                worst = s
            components.append({
                "name": f"fleet:{rec.instance}",
                "kind": "fleet",
                "status": st,
                "detail": (f"no push for {age:.1f}s" if stale else
                           f"last push {age:.1f}s ago (seq {rec.seq})"),
                "role": rec.role,
                "push_age_s": age,
                "via": rec.via,
                "components": len(rec.health.get("components", [])),
            })
        return {
            "status": _health.status_string(worst),
            "ok": worst <= _health.Status.DEGRADED,
            "components": components,
            "fleet": {"instances": len(recs)},
        }

    def slo_rollup(self, local: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
        """Fleet-wide SLO view for ``/debug/slo``: each live instance's
        pushed per-tenant snapshot (plus this process's own when given),
        and the tenants breaching their burn budget anywhere in the
        fleet — the page an operator reads before asking which worker
        to drain."""
        self._expire_now()
        with self._lock:
            recs = list(self._instances.values())
        instances: Dict[str, Any] = {}
        breached: set = set()

        def scan(iid: str, snap: Optional[Dict[str, Any]]) -> None:
            if not isinstance(snap, dict) or not snap.get("enabled"):
                return
            instances[iid] = snap
            for tenant, row in (snap.get("tenants") or {}).items():
                burn = row.get("burn") if isinstance(row, dict) else None
                if isinstance(burn, dict) and burn.get("breached"):
                    breached.add(tenant)

        if local is not None:
            scan(self.instance, local)
        for rec in recs:
            scan(rec.instance, rec.slo)
        return {"instances": instances, "breached": sorted(breached)}

    def ready_rollup(self, local_ready: bool,
                     local_conds: Dict[str, bool]
                     ) -> Tuple[bool, Dict[str, bool]]:
        """Fleet /readyz: local readiness AND every live instance both
        fresh and self-reporting ready."""
        self._expire_now()
        now = time.monotonic()
        conds = dict(local_conds)
        with self._lock:
            recs = list(self._instances.values())
        for rec in recs:
            fresh = (now - rec.last_mono) <= self._ttl(rec)
            conds[f"fleet:{rec.instance}"] = \
                fresh and bool(rec.ready.get("ready"))
        return local_ready and all(conds.values()), conds

    # -- routing view ------------------------------------------------------ #
    @staticmethod
    def _queue_depth(rec: _Instance) -> float:
        """Instance load as one plain scalar: the sum of every series
        in its pushed queue-depth gauge families. Buried sub-doc → a
        number a placement loop can compare without parsing."""
        total = 0.0
        for fam_name in QUEUE_DEPTH_FAMILIES:
            fam = rec.metrics.get(fam_name)
            if not isinstance(fam, dict):
                continue
            for series in fam.get("series") or ():
                try:
                    total += float(series.get("value", 0.0))
                except (TypeError, ValueError):
                    continue
        return total

    def routing_view(self) -> Dict[str, Dict[str, Any]]:
        """Per-instance placement signals as plain scalars — what the
        query router consumes. Each live instance maps to::

            {"routable": bool,   # fresh AND self-reported ready
             "ready": bool, "stale": bool, "queue_depth": float,
             "role": str, "push_age_s": float}

        An EXPIRED instance stays in the view as a tombstone
        (``routable=False, expired=True``) instead of vanishing — a
        router must read "known dead", never mistake absence for
        "never existed"."""
        self._expire_now()
        now = time.monotonic()
        with self._lock:
            recs = list(self._instances.values())
            stones = {iid: dict(t) for iid, t in self._tombstones.items()}
        view: Dict[str, Dict[str, Any]] = {}
        for rec in recs:
            age = now - rec.last_mono
            stale = age > self._ttl(rec)
            ready = bool(rec.ready.get("ready"))
            view[rec.instance] = {
                "routable": (not stale) and ready,
                "ready": ready,
                "stale": stale,
                "queue_depth": self._queue_depth(rec),
                "role": rec.role,
                "push_age_s": age,
                "kv_prefix_size": len(rec.kv_prefix or ()),
            }
        for iid, stone in stones.items():
            if iid in view:
                continue
            view[iid] = {
                "routable": False,
                "ready": False,
                "stale": True,
                "expired": True,
                "queue_depth": float("inf"),
                "role": stone.get("role", "worker"),
                "push_age_s": now - float(stone.get("expired_mono", now)),
                "kv_prefix_size": 0,
            }
        return view

    def scale_signals(self) -> Dict[str, Any]:
        """Controller-facing snapshot (fleet/controller.observe): the
        routing view reduced to the scalars the autoscale policy
        prices — total finite queue depth over routable instances, the
        routable census, and the fleet's breached-tenant list."""
        view = self.routing_view()
        queue_depth, routable = 0.0, 0
        for row in view.values():
            if not row.get("routable"):
                continue
            routable += 1
            depth = float(row.get("queue_depth", 0.0))
            if depth != float("inf"):
                queue_depth += depth
        return {"queue_depth": queue_depth, "routable": routable,
                "breached": self.slo_rollup()["breached"],
                "instances": len(view)}

    def actions_rollup(self) -> Dict[str, Any]:
        """Fleet-wide autoscale action journals (``/debug/fleet/
        actions``): every live instance's pushed journal, keyed by
        instance — who scaled what, when, and why."""
        self._expire_now()
        with self._lock:
            recs = list(self._instances.values())
        return {rec.instance: rec.actions for rec in recs
                if rec.actions is not None}

    def checkpoints_rollup(self) -> Dict[str, Any]:
        """Fleet-wide checkpoint state (``/debug/fleet/checkpoints``):
        every live instance's pushed watermarks keyed by instance,
        plus the tombstoned instances whose watermarks still await a
        restore — the one view an operator scans to answer "whose
        sessions are covered, and who died holding coverage"."""
        self._expire_now()
        with self._lock:
            recs = list(self._instances.values())
            pending = [
                {"instance": iid,
                 "endpoint": stone.get("endpoint"),
                 "sessions": len(stone.get("checkpoints") or {}),
                 "consumed": bool(stone.get("restore_consumed"))}
                for iid, stone in self._tombstones.items()
                if "checkpoints" in stone or stone.get("restore_consumed")]
        return {
            "instances": {rec.instance: {"endpoint": rec.endpoint,
                                         "checkpoints": rec.checkpoints}
                          for rec in recs
                          if rec.checkpoints is not None},
            "pending_restore": pending,
        }

    def diag_rollup(self) -> Dict[str, Any]:
        """Fleet-wide incident evidence (``/debug/bundles``): every
        live instance's pushed bundle references + trigger accounting,
        keyed by instance — given one incident's time window, this
        enumerates which instances captured evidence for it and which
        bundle ids to fetch from whom."""
        self._expire_now()
        with self._lock:
            recs = list(self._instances.values())
        return {rec.instance: rec.diag for rec in recs
                if rec.diag is not None}

    def quality_rollup(self) -> Dict[str, Any]:
        """Fleet-wide data-plane quality (``/debug/quality``): every
        live instance's pushed per-tap summary keyed by instance, plus
        the flattened ``anomalous`` list (``instance/tap``) — the one
        line an operator scans to find which instance's which tap is
        producing garbage."""
        self._expire_now()
        with self._lock:
            recs = list(self._instances.values())
        per_instance = {rec.instance: rec.quality for rec in recs
                        if rec.quality is not None}
        anomalous = sorted(
            f"{iid}/{tap}"
            for iid, doc in per_instance.items()
            for tap in (doc.get("anomalies") or {}))
        return {"instances": per_instance, "anomalous": anomalous}

    def longest_prefix(self, hashes: Sequence[str]
                       ) -> Tuple[Optional[str], int]:
        """The routable instance holding the longest shared KV prefix.

        ``hashes`` is the request's chained page-path hash list
        (kv_cache.prompt_path_hashes): because each hash chains over
        its whole path, digest membership of ``hashes[i]`` proves the
        instance holds pages 0..i — the probe is i set lookups, and it
        stops at the first miss. Returns ``(instance, depth)`` where
        depth counts matched leading pages, or ``(None, 0)`` when no
        fresh+ready instance advertises any of the prefix. Only
        instances that would be ``routable`` in :meth:`routing_view`
        are considered — a stale digest must not attract placements."""
        if not hashes:
            return None, 0
        self._expire_now()
        now = time.monotonic()
        with self._lock:
            recs = list(self._instances.values())
        best: Optional[str] = None
        best_depth = 0
        for rec in recs:
            dig = rec.kv_prefix
            if not dig or not rec.ready.get("ready") \
                    or now - rec.last_mono > self._ttl(rec):
                continue
            depth = 0
            for h in hashes:
                if h not in dig:
                    break
                depth += 1
            if depth > best_depth:
                best, best_depth = rec.instance, depth
        return best, best_depth

    def tuned_view(self) -> Optional[Dict[str, Any]]:
        """The fleet's merged autotuned-config doc: the union of every
        instance's pushed tune slice, lowest measured cost winning per
        key (latest timestamp breaking unknown-cost ties). This is what
        the push-ack carries back to workers — an instance's sweep
        result reaches its peers one push interval later. None while no
        instance has pushed any tune data, so pre-tune acks stay
        byte-identical."""
        with self._lock:
            docs = [rec.tune for rec in self._instances.values()
                    if isinstance(rec.tune, dict)]
        merged: Dict[str, Dict[str, Any]] = {}
        for doc in docs:
            ents = doc.get("entries")
            if not isinstance(ents, dict):
                continue
            for k, rec in ents.items():
                if not isinstance(rec, dict) or "value" not in rec:
                    continue
                cur = merged.get(k)
                if cur is not None:
                    rc, cc = rec.get("cost_us"), cur.get("cost_us")
                    if cc is not None:
                        # a measured incumbent yields only to a
                        # strictly better measurement
                        if rc is None or rc >= cc:
                            continue
                    elif rc is None and (rec.get("ts") or 0) <= \
                            (cur.get("ts") or 0):
                        continue  # both unmeasured: newest wins
                merged[k] = rec
        if not merged:
            return None
        return {"version": 1, "entries": merged}

    # -- /debug/fleet ------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Any]:
        self._expire_now()
        now = time.monotonic()
        with self._lock:
            recs = list(self._instances.values())
            stones = list(self._tombstones)
        instances = []
        for rec in recs:
            age = now - rec.last_mono
            instances.append({
                "instance": rec.instance,
                "role": rec.role,
                "seq": rec.seq,
                "via": rec.via,
                "pushes": rec.pushes,
                "push_age_s": age,
                "ttl_s": self._ttl(rec),
                "stale": age > self._ttl(rec),
                "interval_s": rec.interval_s,
                "families": len(rec.metrics),
                "spans_ingested": rec.spans_ingested,
                "health_status": rec.health.get("status"),
                "ready": bool(rec.ready.get("ready")),
                "queue_depth": self._queue_depth(rec),
            })
        return {
            "aggregator": {"instance": self.instance, "role": self.role},
            "pushes_ingested": self.pushes_ingested,
            "bad_pushes": self.bad_pushes,
            "instances": instances,
            "expired": stones,
        }

    def close(self) -> None:
        with self._lock:
            self._instances.clear()


# --------------------------------------------------------------------------- #
# Module-global pusher + aggregator
# --------------------------------------------------------------------------- #

_PUSHER: Optional[FleetPusher] = None
_AGGREGATOR: Optional[FleetAggregator] = None


def pusher() -> Optional[FleetPusher]:
    return _PUSHER


def push_enabled() -> bool:
    return _PUSHER is not None


def enable_push(url: Optional[str] = None,
                interval_s: float = DEFAULT_INTERVAL_S,
                role: str = "worker",
                instance: Optional[str] = None) -> FleetPusher:
    """Start the process-global fleet pusher. ``url=None`` is wire-only
    (pushes piggyback on query-client traffic; no thread). Replaces a
    previous pusher. Also enables metric collection — pushing a
    disabled registry's empty snapshot would be all gaps."""
    global _PUSHER
    if _PUSHER is not None:
        _PUSHER.close()
    _metrics.enable()
    _PUSHER = FleetPusher(url=url, interval_s=interval_s, role=role,
                          instance=instance)
    return _PUSHER


def disable_push() -> None:
    global _PUSHER
    if _PUSHER is not None:
        _PUSHER.close()
        _PUSHER = None


def wire_frame_due() -> Optional[Tuple[Dict[str, Any], bytes]]:
    """THE query-client fast path: one module-global read when fleet
    push is off — no frame, no bytes, no allocation."""
    p = _PUSHER
    return p.wire_frame() if p is not None else None


def aggregator() -> Optional[FleetAggregator]:
    return _AGGREGATOR


def enable_aggregator(ttl_s: Optional[float] = None,
                      expire_after_s: Optional[float] = None
                      ) -> FleetAggregator:
    """Turn this process into the fleet aggregator: the exporter's
    ``/metrics``, ``/healthz``, ``/readyz`` switch to the merged fleet
    views, ``POST /fleet/push`` and ``GET /debug/fleet`` activate, and
    ``OBS_PUSH`` frames arriving on any serversrc are ingested."""
    global _AGGREGATOR
    if _AGGREGATOR is None:
        _AGGREGATOR = FleetAggregator(ttl_s=ttl_s,
                                      expire_after_s=expire_after_s)
    else:
        if ttl_s is not None:
            _AGGREGATOR.ttl_s = ttl_s
        if expire_after_s is not None:
            _AGGREGATOR.expire_after_s = expire_after_s
    return _AGGREGATOR


def disable_aggregator() -> None:
    global _AGGREGATOR
    if _AGGREGATOR is not None:
        _AGGREGATOR.close()
        _AGGREGATOR = None


def ingest_wire(meta: Dict[str, Any], payload: bytes) -> None:
    """Server-side ``OBS_PUSH`` handler: decode and ingest when this
    process aggregates, count-and-drop otherwise. Never raises into
    the connection loop — a worker's bad push must not kill the
    client's data stream."""
    agg = _AGGREGATOR
    if agg is None:
        return
    try:
        agg.ingest(json.loads(payload or b"{}"), via="wire")
    except Exception as e:  # noqa: BLE001 — the contract in the docstring
        _events.record("fleet.bad_push",
                       f"undecodable wire push from "
                       f"{meta.get('instance', '?')}: {e}",
                       severity="warning",
                       instance=str(meta.get("instance", "?")))
