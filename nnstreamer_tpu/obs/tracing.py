"""Span-based request tracing with cross-wire context propagation.

PR 1's metrics answer "how slow is this element on average"; they
cannot answer "where did *this* slow request spend its time" across
client → query wire → server pipeline → serving engine. This module is
the per-request complement: explicit span contexts (``trace_id`` /
``span_id`` / ``parent_id``), a lock-protected bounded span store with
**tail-based retention** (the slowest-N completed traces are always
kept alongside a ring of recent ones — tail-latency forensics wants
exactly the traces a uniform sample would evict), and the same
zero-overhead-when-disabled flag discipline as the metrics registry.

Context travels three ways:

  * **in-process** on ``Buffer.meta[CTX_META_KEY]`` — the source stamps
    a root span, every instrumented element chain opens a child
    (obs/instrument.py), sinks close the root;
  * **cross-thread** via a ``contextvars`` current-span slot set while
    an instrumented chain or a ``with start_span(...)`` body runs, so
    engine ``submit()`` calls made inside a traced chain join the
    trace without plumbing;
  * **cross-wire** as a ``trace`` field in query message meta
    (query/protocol.py) — the server adopts the remote parent, so one
    trace id spans both processes.

Span names are literal ``<layer>.<operation>`` lowercase dotted
strings (layer in {pipeline, query, serving, device}), linted by
scripts/check_metric_names.py alongside the metric names.

Exposition: ``GET /debug/traces`` (summaries, ``?min_ms=`` filter),
``GET /debug/traces/<trace_id>`` (full span tree) and
``GET /debug/pipeline`` (live topology + per-element span stats, the
DOT-dump analog) on the obs exporter. ``nns-launch --trace`` and
``PipelineTracer`` consume the same store. Stdlib only.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import OrderedDict
from collections import deque as _deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Span", "SpanContext", "SpanStore", "CTX_META_KEY", "ROOT_META_KEY",
    "TRACE_META_KEY", "ctx_from_wire", "current_context", "disable",
    "enable", "enabled", "element_stats", "element_stats_report",
    "live_pipelines", "pipeline_topology", "register_pipeline",
    "stamp_buffer", "start_span", "store",
]

#: Buffer.meta key carrying the in-process parent SpanContext
CTX_META_KEY = "trace_ctx"
#: Buffer.meta key carrying the root Span a sink must close
ROOT_META_KEY = "trace_root"
#: wire meta key carrying {"tid": trace_id, "sid": span_id}
TRACE_META_KEY = "trace"


def _new_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """Immutable (trace_id, span_id, parent_id) triple. ``parent_id``
    is None for a locally-rooted span; a remote parent (adopted off the
    wire) is a plain SpanContext whose ids came from the peer."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def to_wire(self) -> Dict[str, str]:
        """The meta["trace"] payload: trace id + this span as the
        remote parent. parent_id is a local concern and stays home."""
        return {"tid": self.trace_id, "sid": self.span_id}

    def __repr__(self) -> str:
        return (f"SpanContext({self.trace_id}, {self.span_id}, "
                f"parent={self.parent_id})")


def ctx_from_wire(d: Any) -> Optional[SpanContext]:
    """Adopt a remote parent from a wire ``trace`` field; None for a
    missing or malformed field (a peer must never crash the receiver
    with a bad trace blob)."""
    if not isinstance(d, dict):
        return None
    tid, sid = d.get("tid"), d.get("sid")
    if not isinstance(tid, str) or not isinstance(sid, str):
        return None
    return SpanContext(tid, sid)


#: current span context for the running thread of control — set while
#: an instrumented element chain or a ``with start_span(...)`` body
#: runs, read by send_message (wire injection) and LMEngine.submit
_current: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("nnstpu_current_span", default=None)


def current_context() -> Optional[SpanContext]:
    return _current.get()


def _set_current(ctx: Optional[SpanContext]):
    return _current.set(ctx)


def _reset_current(token) -> None:
    _current.reset(token)


class Span:
    """One timed operation. Created by ``SpanStore.start_span``; calling
    ``end()`` (idempotent) records it into the store. Usable as a
    context manager: exceptions set ``error=True`` before ending."""

    __slots__ = ("name", "context", "start_ns", "end_ns", "wall",
                 "attrs", "tid", "_store", "_token")
    recording = True

    def __init__(self, store: "SpanStore", name: str, context: SpanContext,
                 attrs: Optional[Dict[str, Any]] = None):
        self._store = store
        self.name = name
        self.context = context
        self.attrs = dict(attrs) if attrs else {}
        self.start_ns = time.monotonic_ns()
        self.wall = time.time()
        self.end_ns: Optional[int] = None
        # creating thread: the Perfetto exporter lays host spans out in
        # one lane per pipeline thread
        self.tid = threading.get_ident()
        self._token = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def end(self) -> None:
        if self.end_ns is not None:
            return  # idempotent: tee'd buffers may reach two sinks
        self.end_ns = time.monotonic_ns()
        self._store._record(self)

    @property
    def duration_ns(self) -> int:
        return (self.end_ns or time.monotonic_ns()) - self.start_ns

    def __enter__(self) -> "Span":
        self._token = _set_current(self.context)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _reset_current(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs["error"] = True
        self.end()


class _NoopSpan:
    """Returned when tracing is disabled: every operation is a no-op
    and ``context`` is None, so callers never stamp wire meta or buffer
    meta from it. One shared instance — zero allocation when off."""

    __slots__ = ()
    recording = False
    context = None
    name = ""
    attrs: Dict[str, Any] = {}
    duration_ns = 0

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _Trace:
    """Span accumulator for one trace id (store-internal; guarded by
    the store lock)."""

    __slots__ = ("spans", "start_ns", "end_ns", "root_name",
                 "duration_ns", "completed", "wall")

    def __init__(self) -> None:
        self.spans: List[Span] = []
        self.start_ns: Optional[int] = None
        self.end_ns: Optional[int] = None
        self.root_name: Optional[str] = None
        self.duration_ns: int = 0
        self.completed = False
        self.wall: Optional[float] = None


class SpanStore:
    """Thread-safe bounded trace store with tail-based retention.

    Capacity is ``max_traces`` recent traces PLUS up to ``keep_slowest``
    protected slots: when the ring wraps, the oldest trace NOT in the
    slowest-N set is evicted, so the worst tail survives arbitrarily
    long runs. A trace is *completed* when a locally-rooted span
    (parent_id None) ends; its duration ranks it. Remote-parented
    server-side traces complete on the client side in two-process
    deployments — in-proc tests see both halves in one store.
    """

    def __init__(self, max_traces: int = 256, keep_slowest: int = 16,
                 max_spans_per_trace: int = 512, enabled: bool = False,
                 sample_every: int = 1):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, _Trace]" = OrderedDict()  # guarded-by: _lock
        self._slow: Dict[str, int] = {}  # trace_id->duration_ns # guarded-by: _lock
        self.max_traces = int(max_traces)
        self.keep_slowest = int(keep_slowest)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.sample_every = max(int(sample_every), 1)
        self._sample_n = 0
        self._enabled = bool(enabled)
        self._dropped_spans = 0
        # -- fleet span export (obs/fleet.py) --------------------------- #
        # Off until a fleet pusher flips it on: zero cost for plain
        # single-process tracing (one attribute read in _record against
        # an empty set). Traces are *marked* exportable when their id
        # crosses the query wire (or an engine opts a request in);
        # spans of marked traces queue — bounded, drop-oldest — for the
        # pusher to drain into the aggregator.
        self._export_on = False
        self._export_tids: "OrderedDict[str, None]" = OrderedDict()
        self._export_max_tids = 4096
        self._export_pending: "deque" = _deque(maxlen=2048)
        self._export_dropped = 0

    # -- enable/disable ------------------------------------------------ #
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._slow.clear()
            self._sample_n = 0
            self._dropped_spans = 0
            self._export_tids.clear()
            self._export_pending.clear()
            self._export_dropped = 0

    # -- recording ----------------------------------------------------- #
    def start_span(self, name: str,
                   parent: Optional[SpanContext] = None,
                   attrs: Optional[Dict[str, Any]] = None):
        """Open a span; the single flag check is the whole disabled
        cost. ``parent=None`` roots a new trace."""
        if not self._enabled:
            return NOOP_SPAN
        if parent is not None:
            ctx = SpanContext(parent.trace_id, _new_id(), parent.span_id)
        else:
            ctx = SpanContext(_new_id(), _new_id(), None)
        return Span(self, name, ctx, attrs)

    def should_sample(self) -> bool:
        """Head sampling for buffer-rate roots: admit 1 of every
        ``sample_every`` new traces (tail retention still keeps the
        slowest of the admitted ones)."""
        if not self._enabled:
            return False
        if self.sample_every <= 1:
            return True
        with self._lock:
            self._sample_n += 1
            return self._sample_n % self.sample_every == 1

    def _record(self, span: Span) -> None:
        tid = span.context.trace_id
        with self._lock:
            tr = self._traces.get(tid)
            if tr is None:
                tr = _Trace()
                self._traces[tid] = tr
            if len(tr.spans) >= self.max_spans_per_trace:
                self._dropped_spans += 1
            else:
                tr.spans.append(span)
                if self._export_on and tid in self._export_tids:
                    if len(self._export_pending) == \
                            self._export_pending.maxlen:
                        self._export_dropped += 1
                    self._export_pending.append(_span_to_wire(span))
            if tr.start_ns is None or span.start_ns < tr.start_ns:
                tr.start_ns = span.start_ns
                tr.wall = span.wall
            if tr.end_ns is None or span.end_ns > tr.end_ns:
                tr.end_ns = span.end_ns
            if span.context.parent_id is None:
                tr.completed = True
                tr.root_name = span.name
                tr.duration_ns = span.end_ns - span.start_ns
                self._rank_slow(tid, tr.duration_ns)
            self._evict_locked()

    def _rank_slow(self, tid: str, duration_ns: int) -> None:  # guarded-by: _lock
        # maintain the protected slowest-N set (store lock held)
        prev = self._slow.get(tid)
        if prev is not None:
            if duration_ns > prev:
                self._slow[tid] = duration_ns
            return
        if len(self._slow) < self.keep_slowest:
            self._slow[tid] = duration_ns
            return
        fastest = min(self._slow, key=self._slow.get)
        if duration_ns > self._slow[fastest]:
            del self._slow[fastest]
            self._slow[tid] = duration_ns

    def _evict_locked(self) -> None:
        budget = self.max_traces + len(self._slow)
        while len(self._traces) > budget:
            victim = None
            for tid in self._traces:  # oldest-first insertion order
                if tid not in self._slow:
                    victim = tid
                    break
            if victim is None:
                return  # everything is protected; nothing to drop
            del self._traces[victim]

    # -- queries -------------------------------------------------------- #
    def summaries(self, min_ms: float = 0.0) -> List[Dict[str, Any]]:
        """Trace list, slowest first; ``min_ms`` filters on duration
        (completed traces only when a threshold is set — an open trace
        has no defensible duration yet)."""
        out = []
        with self._lock:
            items = list(self._traces.items())
        for tid, tr in items:
            dur_ms = tr.duration_ns / 1e6 if tr.completed else None
            if min_ms > 0.0 and (dur_ms is None or dur_ms < min_ms):
                continue
            out.append({
                "trace_id": tid,
                "root": tr.root_name,
                "completed": tr.completed,
                "duration_ms": dur_ms,
                "spans": len(tr.spans),
                "slowest_retained": tid in self._slow,
                "wall": tr.wall,
            })
        out.sort(key=lambda s: s["duration_ms"] or 0.0, reverse=True)
        return out

    def spans_of(self, trace_id: str) -> Optional[List[Span]]:
        with self._lock:
            tr = self._traces.get(trace_id)
            return list(tr.spans) if tr is not None else None

    def tree(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Full span tree for one trace: spans nest under their local
        parents; spans whose parent is remote (or unrecorded) surface
        as roots — exactly the view a cross-process half contributes."""
        spans = self.spans_of(trace_id)
        if spans is None:
            return None
        t0 = min(s.start_ns for s in spans) if spans else 0

        def node(s: Span) -> Dict[str, Any]:
            return {
                "span_id": s.context.span_id,
                "parent_id": s.context.parent_id,
                "name": s.name,
                "start_us": (s.start_ns - t0) / 1e3,
                "duration_us": (s.end_ns - s.start_ns) / 1e3,
                "attrs": s.attrs,
                "children": [],
            }

        by_id = {s.context.span_id: node(s) for s in spans}
        roots: List[Dict[str, Any]] = []
        for n in by_id.values():
            parent = by_id.get(n["parent_id"])
            if parent is not None:
                parent["children"].append(n)
            else:
                roots.append(n)
        for n in by_id.values():
            n["children"].sort(key=lambda c: c["start_us"])
        roots.sort(key=lambda c: c["start_us"])
        return {"trace_id": trace_id, "spans": len(spans), "tree": roots}

    def element_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-element stats over recorded ``pipeline.element`` spans:
        {element: {n, mean_us, max_us}} — the span-store view the
        /debug/pipeline endpoint and ``nns-launch --trace`` render."""
        agg: Dict[str, List[float]] = {}
        with self._lock:
            traces = list(self._traces.values())
        for tr in traces:
            for s in tr.spans:
                if s.name != "pipeline.element":
                    continue
                el = str(s.attrs.get("element", "?"))
                agg.setdefault(el, []).append(
                    (s.end_ns - s.start_ns) / 1e3)
        return {
            el: {"n": len(v), "mean_us": sum(v) / len(v), "max_us": max(v)}
            for el, v in agg.items()
        }

    def snapshot_spans(self, max_spans: int = 20000) -> List[Span]:
        """Flat snapshot of recorded spans across all retained traces
        (completed spans only), for timeline exporters (obs/profile.py's
        Perfetto view). Bounded: retention already caps traces, this
        caps the flattened view."""
        out: List[Span] = []
        with self._lock:
            for tr in self._traces.values():
                for s in tr.spans:
                    if s.end_ns is not None:
                        out.append(s)
                        if len(out) >= max_spans:
                            return out
        return out

    def add_span(self, name: str, trace_id: str, parent_id: Optional[str],
                 start_ns: int, end_ns: int,
                 attrs: Optional[Dict[str, Any]] = None,
                 wall: Optional[float] = None) -> Optional[SpanContext]:
        """Insert one already-timed span into an existing trace — the
        diag layer's entry for synthetic attribution spans (sched queue
        wait / batch run) whose endpoints were measured outside a
        ``with start_span(...)`` body. Timestamps are local monotonic
        ns; the span records immediately (bypassing ``end()``, which
        would re-stamp ``end_ns``). Returns the new span's context, or
        None when the store is disabled."""
        if not self._enabled:
            return None
        ctx = SpanContext(str(trace_id), _new_id(), parent_id or None)
        span = Span.__new__(Span)
        span._store = self
        span.name = str(name)
        span.context = ctx
        span.attrs = dict(attrs) if attrs else {}
        span.start_ns = int(start_ns)
        span.end_ns = max(int(end_ns), int(start_ns))
        span.wall = float(wall) if wall is not None else (
            time.time() - (time.monotonic_ns() - span.start_ns) / 1e9)
        span.tid = threading.get_ident()
        span._token = None
        self._record(span)
        return ctx

    # -- fleet span export/ingest (obs/fleet.py) ------------------------ #
    def set_export(self, on: bool) -> None:
        """Flip fleet span export. Off (the default) keeps _record's
        extra cost at one attribute read; turning off also drops any
        queued exports and marks."""
        with self._lock:
            self._export_on = bool(on)
            if not on:
                self._export_tids.clear()
                self._export_pending.clear()

    def mark_export(self, trace_id: Optional[str]) -> None:
        """Mark one trace's spans for fleet export — called where a
        trace id crosses the query wire (send injection / remote-parent
        adoption) and by a serving engine opting a request in. LRU-
        bounded; a no-op unless a fleet pusher enabled export."""
        if not self._export_on or not trace_id:
            return
        with self._lock:
            self._export_tids[trace_id] = None
            self._export_tids.move_to_end(trace_id)
            while len(self._export_tids) > self._export_max_tids:
                self._export_tids.popitem(last=False)

    def drain_export(self, max_n: int = 512) -> List[Dict[str, Any]]:
        """Pop up to ``max_n`` queued wire-format span dicts (oldest
        first) — the fleet pusher's per-push batch."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            while self._export_pending and len(out) < int(max_n):
                out.append(self._export_pending.popleft())
        return out

    def requeue_export(self, spans: List[Dict[str, Any]]) -> None:
        """Put a drained batch back at the FRONT of the export queue —
        the pusher's failure path, so a briefly unreachable aggregator
        doesn't silently lose the spans it drained. Overflow evicts the
        newest queued entries (the requeued batch is older) and counts
        them as export drops."""
        if not spans:
            return
        with self._lock:
            if not self._export_on:
                return
            free = self._export_pending.maxlen - len(self._export_pending)
            overflow = len(spans) - free
            if overflow > 0:
                self._export_dropped += overflow
            for s in reversed(spans):
                self._export_pending.appendleft(s)

    def ingest_remote(self, spans: List[Dict[str, Any]],
                      instance: str) -> int:
        """Insert pushed wire-format spans from ``instance`` into this
        store so /debug/traces/<id> renders the cross-host tree.
        Remote timestamps arrive wall-clock-derived (monotonic clocks
        do not travel between hosts) and are rebased here into the
        local monotonic domain — local spans carry ``monotonic_ns``
        starts, and a trace holding both halves (aggregator tracing its
        own side of the same request) must not mix clock domains in
        tree() offsets or trace start/end rollups. Malformed entries
        are skipped, never raised — a peer must not 500 the aggregator.
        Returns the count actually ingested. Works on a disabled store:
        the aggregator exposes fleet traces without recording its own."""
        # one anchor per batch: local monotonic "now" minus wall "now";
        # remote wall ns + offset lands in the local monotonic domain
        # (to the accuracy of inter-host clock sync, the best we have)
        offset_ns = time.monotonic_ns() - int(time.time() * 1e9)
        n = 0
        for d in spans:
            try:
                ctx = SpanContext(str(d["tid"]), str(d["sid"]),
                                  d.get("par") or None)
                span = Span.__new__(Span)
                span._store = self
                span.name = str(d["name"])
                span.context = ctx
                span.attrs = dict(d.get("attrs") or {})
                span.attrs.setdefault("instance", instance)
                span.wall = float(d["wall"])
                span.start_ns = int(span.wall * 1e9) + offset_ns
                span.end_ns = span.start_ns + max(int(d["dur_ns"]), 0)
                span.tid = 0  # remote thread idents are meaningless here
                span._token = None
            except Exception:
                # the docstring's "never raised" is load-bearing: any
                # malformed field shape (not just the anticipated
                # KeyError/TypeError/ValueError) must skip the entry,
                # not 500 the aggregator
                continue
            # bypass Span.end(): end_ns is already set, record directly
            tid = span.context.trace_id
            with self._lock:
                tr = self._traces.get(tid)
                if tr is None:
                    tr = _Trace()
                    self._traces[tid] = tr
                if len(tr.spans) >= self.max_spans_per_trace:
                    self._dropped_spans += 1
                else:
                    tr.spans.append(span)
                if tr.start_ns is None or span.start_ns < tr.start_ns:
                    tr.start_ns = span.start_ns
                    tr.wall = span.wall
                if tr.end_ns is None or span.end_ns > tr.end_ns:
                    tr.end_ns = span.end_ns
                if span.context.parent_id is None:
                    tr.completed = True
                    tr.root_name = span.name
                    tr.duration_ns = span.end_ns - span.start_ns
                    self._rank_slow(tid, tr.duration_ns)
                self._evict_locked()
            n += 1
        return n


def _span_to_wire(span: Span) -> Dict[str, Any]:
    """Wire-format dict for one completed span: wall-clock start +
    duration (monotonic ns never leave the host), ids, name, attrs."""
    return {
        "tid": span.context.trace_id,
        "sid": span.context.span_id,
        "par": span.context.parent_id,
        "name": span.name,
        "wall": span.wall,
        "dur_ns": (span.end_ns or span.start_ns) - span.start_ns,
        "attrs": span.attrs,
    }


# --------------------------------------------------------------------------- #
# Process-global store + helpers
# --------------------------------------------------------------------------- #

#: disabled by default — mirror of the metrics registry: tracing costs
#: one flag check until NNSTPU_TRACE=1 or enable() turns it on
_STORE = SpanStore(enabled=os.environ.get("NNSTPU_TRACE", "") == "1")


def store() -> SpanStore:
    return _STORE


def enabled() -> bool:
    return _STORE._enabled


def enable(sample_every: Optional[int] = None) -> None:
    """Turn span recording on. Like metrics, call BEFORE building
    pipelines/starting them: element chains decide at Pipeline.start
    whether to open spans at all."""
    if sample_every is not None:
        _STORE.sample_every = max(int(sample_every), 1)
    _STORE.enable()


def disable() -> None:
    _STORE.disable()


def start_span(name: str, parent: Optional[SpanContext] = None,
               attrs: Optional[Dict[str, Any]] = None):
    return _STORE.start_span(name, parent=parent, attrs=attrs)


def stamp_buffer(buf: Any, span_store: SpanStore, source: str):
    """Root a new trace on a source-created buffer (obs/instrument.py
    source wrapper). A buffer that already carries a context — e.g. a
    serversrc inbox frame adopted off the wire — is left alone: the
    existing trace owns it."""
    if CTX_META_KEY in buf.meta:
        return None
    if not span_store.should_sample():
        return None
    root = span_store.start_span("pipeline.buffer", attrs={
        "source": source, "pts": buf.pts, "offset": buf.offset})
    if root.recording:
        buf.meta[CTX_META_KEY] = root.context
        buf.meta[ROOT_META_KEY] = root
    return root


# -- live pipeline topology (the DOT-dump analog) --------------------------- #

import weakref  # noqa: E402 — grouped with its single consumer

_live_pipelines: "weakref.WeakSet" = weakref.WeakSet()


def register_pipeline(pipeline: Any) -> None:
    """Called from the Pipeline.start instrumentation hook — a WeakSet
    add, so a collected pipeline never lingers in /debug/pipeline."""
    _live_pipelines.add(pipeline)


def live_pipelines() -> List[Any]:
    return list(_live_pipelines)


def pipeline_topology(pipeline: Any) -> Dict[str, Any]:
    """Elements + directed links of one pipeline, duck-typed off the
    graph model (element name/kind, src pad → peer element)."""
    elements = []
    for el in pipeline.elements.values():
        links = []
        for pad in el.src_pads:
            if pad.peer is not None:
                links.append(pad.peer.element.name)
        elements.append({
            "name": el.name,
            "kind": getattr(el, "ELEMENT_NAME", type(el).__name__),
            "is_source": el.is_source,
            "is_sink": el.is_sink,
            "links": links,
        })
    return {"name": pipeline.name, "running": pipeline.running,
            "elements": elements}


def element_stats(span_store: Optional[SpanStore] = None
                  ) -> Dict[str, Dict[str, float]]:
    return (span_store or _STORE).element_stats()


def element_stats_report(span_store: Optional[SpanStore] = None) -> str:
    """Text table of per-element span stats, slowest mean first — the
    shared renderer behind ``nns-launch --trace`` and
    ``PipelineTracer.span_report``."""
    stats = element_stats(span_store)
    lines = [f"{'element':<24}{'spans':>8}{'mean(us)':>12}{'max(us)':>12}"]
    for el, t in sorted(stats.items(),
                        key=lambda kv: kv[1]["mean_us"], reverse=True):
        lines.append(f"{el:<24}{t['n']:>8}{t['mean_us']:>12.1f}"
                     f"{t['max_us']:>12.1f}")
    return "\n".join(lines)
