"""Health model: component liveness registry + stall watchdog.

The dangerous failure mode of a long-running streaming graph is not a
crash but a silent stall — an element stops pulling, a query peer
half-disconnects, a serving request sits in admission forever. Metrics
(PR 1) and traces (PR 2) say how fast the system is; this module says
whether it is *alive*.

**Components** are named liveness reporters registered by the pipeline
instrumentation (one per element), the query client/server elements,
and the serving engines. Each carries a :class:`Status` — OK <
DEGRADED < STALLED < FAILED, ordered so the aggregate is a ``max()`` —
a free-form detail string, a last-heartbeat stamp (``beat()``, written
by the obs/instrument.py chain wrappers per buffer), monotonically
increasing event counts (``count()``), and an optional ``probe``
callable returning a point-in-time dict (queue depth, engine wait...).
A probe returning None retires its component (weakref-backed probes:
the registry never pins a dead pipeline or engine).

**The watchdog** is one daemon thread (started lazily on first
registration while enabled — never when off) applying these rules each
tick and recording its verdicts as flight-recorder events
(obs/events.py):

  * *element stall*: a running, non-EOS pipeline's element that has
    processed at least one buffer but none for ``stall_after_s`` →
    STALLED (``pipeline.stall`` event with the element name, stall age,
    and the element's last-seen trace id);
  * *queue dwell*: a queue-ish element probe reporting
    ``depth >= bound`` continuously for ``queue_dwell_s`` → DEGRADED
    (``pipeline.queue_full``);
  * *reconnect storm*: a query component whose ``reconnect`` count
    rises by ``reconnect_storm`` within ``reconnect_window_s`` →
    DEGRADED (``query.reconnect_storm``);
  * *admission stall*: a serving engine probe reporting a queued
    request waiting past ``admission_deadline_s`` → STALLED
    (``serving.admission_stall``);
  * *starvation storm*: a sched engine whose starvation-relief count
    rises by ``starvation_storm`` within ``starvation_window_s`` →
    DEGRADED (``sched.starvation_storm``);
  * *SLO burn*: an obs/slo.py tenant whose burn rate breaches its
    error budget on both windows → DEGRADED (``slo.burn_alert``).

Recovery flips the verdict back to OK and records the matching
``<layer>.recover`` event, so flapping is visible.

**Readiness** is a separate axis: named boolean conditions
(pipeline PLAYING, engine warmed = first bucket compiled, query
connected) registered by the same integration points, aggregated by
``readiness()`` and served at ``/readyz`` on the exporter — 503 until
every condition holds (and while none are registered: a server that
has nothing ready yet is not ready). ``/healthz`` stays liveness:
200 while the aggregate is OK/DEGRADED, 503 on STALLED/FAILED.

Same contract as metrics/tracing/events: off by default
(``NNSTPU_HEALTH=1`` or ``enable()`` — BEFORE building pipelines and
engines, like the others), and structurally free while off: no
components, no conditions, no thread, one flag check.
"""

from __future__ import annotations

import enum
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import events as _events

__all__ = [
    "Component", "HealthRegistry", "Status", "add_readiness",
    "component", "check_now", "disable", "enable", "enabled",
    "readiness", "registry", "snapshot", "status_from_string",
    "status_string", "track_pipeline",
]


class Status(enum.IntEnum):
    """Severity-ordered so an aggregate is ``max()`` over components."""

    OK = 0
    DEGRADED = 1
    STALLED = 2
    FAILED = 3


#: /healthz "status" strings; FAILED renders as "failing" (an ongoing
#: condition, not a past event)
_STATUS_STRINGS = {
    Status.OK: "ok",
    Status.DEGRADED: "degraded",
    Status.STALLED: "stalled",
    Status.FAILED: "failing",
}


def status_string(s: Status) -> str:
    return _STATUS_STRINGS[s]


#: inverse map for fleet rollup: a pushed status string from a peer
#: re-enters the severity order; unknown strings rank DEGRADED (a peer
#: speaking a newer grammar is suspicious, not fatal)
_STATUS_BY_STRING = {v: k for k, v in _STATUS_STRINGS.items()}


def status_from_string(s: str) -> Status:
    return _STATUS_BY_STRING.get(s, Status.DEGRADED)


class Component:
    """One liveness reporter. All mutators are lock-free single-field
    writes (GIL-atomic) — they run on buffer hot paths."""

    __slots__ = ("name", "kind", "probe", "attrs", "status", "detail",
                 "since", "last_beat_ns", "last_trace_id", "counts")

    def __init__(self, name: str, kind: str = "generic",
                 probe: Optional[Callable[[], Optional[Dict[str, Any]]]]
                 = None, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.kind = kind
        self.probe = probe
        self.attrs = dict(attrs) if attrs else {}
        self.status = Status.OK
        self.detail = ""
        self.since = time.time()
        self.last_beat_ns: Optional[int] = None
        #: trace id of the last buffer seen (stamped by the chain
        #: wrapper when tracing is on) — watchdog verdicts carry it so
        #: a stall correlates with the trace that stopped moving
        self.last_trace_id: Optional[str] = None
        self.counts: Dict[str, int] = {}

    def beat(self) -> None:
        """Heartbeat: "I just processed work"."""
        self.last_beat_ns = time.monotonic_ns()

    def set_status(self, status: Status, detail: str = "") -> None:
        if status != self.status:
            self.since = time.time()
            if status >= Status.DEGRADED:
                # escalation is the diag capture moment: freeze the
                # evidence rings before they age past the incident
                # (lazy import: diag's collectors read this module)
                from . import diag as _diag
                dhook = _diag.DIAG_HOOK
                if dhook is not None:
                    dhook.on_degraded(self.name, detail)
        self.status = status
        self.detail = detail

    def count(self, key: str, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n

    def snapshot(self, now_ns: Optional[int] = None) -> Dict[str, Any]:
        now_ns = now_ns if now_ns is not None else time.monotonic_ns()
        d: Dict[str, Any] = {
            "name": self.name,
            "kind": self.kind,
            "status": status_string(self.status),
            "detail": self.detail,
            "since": self.since,
            "last_beat_age_s": ((now_ns - self.last_beat_ns) / 1e9
                                if self.last_beat_ns else None),
        }
        if self.counts:
            d["counts"] = dict(self.counts)
        if self.probe is not None:
            try:
                data = self.probe()
            except Exception:  # noqa: BLE001 — a probe must not 500 /healthz
                data = None
            if data is not None:
                d["probe"] = data
        return d


class _NoopComponent:
    """Returned by ``component()`` while health is off: every reporter
    call is a no-op on one shared instance — zero per-site state."""

    __slots__ = ()
    name = ""
    kind = "noop"
    status = Status.OK
    last_trace_id = None

    def beat(self) -> None:
        pass

    def set_status(self, status: Status, detail: str = "") -> None:
        pass

    def count(self, key: str, n: int = 1) -> None:
        pass


NOOP_COMPONENT = _NoopComponent()


class HealthRegistry:
    """Component + readiness-condition registry with the watchdog."""

    def __init__(self, enabled: bool = False):
        self._lock = threading.Lock()
        self._enabled = bool(enabled)
        self._components: "OrderedDict[str, Component]" = OrderedDict()  # guarded-by: _lock
        #: readiness conditions: name -> fn() -> True/False, or None to
        #: self-retire (weakref-backed: owner collected)
        self._conditions: "OrderedDict[str, Callable]" = OrderedDict()
        #: per-component watchdog bookkeeping (verdict flags, windows)
        self._wd_state: Dict[str, Dict[str, Any]] = {}
        self._wd_thread: Optional[threading.Thread] = None
        self._wd_stop = threading.Event()
        # thresholds (configure()/enable() override)
        self.stall_after_s = 5.0
        self.queue_dwell_s = 5.0
        self.reconnect_storm = 5
        self.reconnect_window_s = 10.0
        self.admission_deadline_s = 30.0
        self.starvation_storm = 3
        self.starvation_window_s = 10.0
        self.interval_s: Optional[float] = None  # None = stall_after/4

    # -- enable/disable ------------------------------------------------ #
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def configure(self, **thresholds: Any) -> None:
        for k, v in thresholds.items():
            if v is None:
                continue
            if not hasattr(self, k):
                raise TypeError(f"unknown health threshold {k!r}")
            setattr(self, k, v)

    def enable(self, **thresholds: Any) -> None:
        self.configure(**thresholds)
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False
        self._stop_watchdog()

    def reset(self) -> None:
        """Drop all components/conditions and stop the watchdog
        (tests)."""
        self._stop_watchdog()
        with self._lock:
            self._components.clear()
            self._conditions.clear()
            self._wd_state.clear()

    # -- registration -------------------------------------------------- #
    def component(self, name: str, kind: str = "generic",
                  probe: Optional[Callable] = None,
                  attrs: Optional[Dict[str, Any]] = None):
        """Get-or-create a component; the shared no-op while disabled
        (the structural fast path: nothing is ever registered)."""
        if not self._enabled:
            return NOOP_COMPONENT
        with self._lock:
            c = self._components.get(name)
            if c is None:
                c = Component(name, kind, probe, attrs)
                self._components[name] = c
            else:
                if probe is not None:
                    c.probe = probe
                if attrs:
                    c.attrs.update(attrs)
        self._ensure_watchdog()
        return c

    def add_readiness(self, name: str, fn: Callable) -> None:
        """Register a readiness condition; no-op while disabled."""
        if not self._enabled:
            return
        with self._lock:
            self._conditions[name] = fn
        self._ensure_watchdog()

    # -- aggregation ---------------------------------------------------- #
    def aggregate(self) -> Status:
        with self._lock:
            comps = list(self._components.values())
        worst = Status.OK
        for c in comps:
            if c.status > worst:
                worst = c.status
        return worst

    def snapshot(self) -> Dict[str, Any]:
        """The /healthz body core: aggregate status string, liveness
        verdict, and per-component detail."""
        if not self._enabled:
            return {"status": "ok", "ok": True, "components": []}
        now_ns = time.monotonic_ns()
        with self._lock:
            comps = list(self._components.values())
        agg = Status.OK
        for c in comps:
            if c.status > agg:
                agg = c.status
        return {
            "status": status_string(agg),
            # liveness: DEGRADED still serves; STALLED/FAILED does not
            "ok": agg <= Status.DEGRADED,
            "components": [c.snapshot(now_ns) for c in comps],
        }

    def readiness(self) -> Tuple[bool, Dict[str, bool]]:
        """(ready, {condition: holds}). Disabled health → vacuously
        ready (the endpoint must not fail deployments that never opted
        in); enabled with zero conditions → NOT ready (nothing has
        declared itself ready yet)."""
        if not self._enabled:
            return True, {}
        with self._lock:
            conds = list(self._conditions.items())
        out: Dict[str, bool] = {}
        dead: List[str] = []
        for name, fn in conds:
            try:
                v = fn()
            except Exception:  # noqa: BLE001
                v = False
            if v is None:
                dead.append(name)
                continue
            out[name] = bool(v)
        if dead:
            with self._lock:
                for name in dead:
                    self._conditions.pop(name, None)
        return bool(out) and all(out.values()), out

    # -- watchdog ------------------------------------------------------- #
    def _interval(self) -> float:
        if self.interval_s is not None:
            return max(float(self.interval_s), 0.01)
        return min(max(float(self.stall_after_s) / 4.0, 0.05), 1.0)

    def _ensure_watchdog(self) -> None:
        if self._wd_thread is not None and self._wd_thread.is_alive():
            return
        self._wd_stop.clear()
        self._wd_thread = threading.Thread(
            target=self._wd_loop, daemon=True, name="obs-health-watchdog")
        self._wd_thread.start()

    def _stop_watchdog(self) -> None:
        self._wd_stop.set()
        t = self._wd_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._wd_thread = None

    def _wd_loop(self) -> None:
        while not self._wd_stop.wait(self._interval()):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — the watchdog never dies
                pass

    def check_now(self) -> None:
        """One synchronous watchdog pass (the thread's tick; callable
        directly for deterministic tests)."""
        now_ns = time.monotonic_ns()
        with self._lock:
            comps = list(self._components.items())
        for name, c in comps:
            data: Optional[Dict[str, Any]] = None
            if c.probe is not None:
                try:
                    data = c.probe()
                except Exception:  # noqa: BLE001 — skip this tick
                    continue
                if data is None:
                    # probe says its owner is gone: retire the component
                    with self._lock:
                        self._components.pop(name, None)
                        self._wd_state.pop(name, None)
                    continue
            st = self._wd_state.setdefault(name, {})
            if c.kind == "element":
                self._check_element(c, st, data or {}, now_ns)
            elif c.kind == "query":
                self._check_query(c, st, now_ns)
            elif c.kind == "serving":
                self._check_serving(c, st, data or {})
            elif c.kind == "fleet":
                self._check_fleet(c, st, data or {})
            elif c.kind == "sched":
                self._check_sched(c, st, data or {}, now_ns)
            elif c.kind == "slo":
                self._check_slo(c, st, data or {})
            elif c.kind == "quality":
                self._check_quality(c, st, data or {})

    # rule: per-element last-buffer heartbeat → STALLED
    def _check_element(self, c: Component, st: Dict[str, Any],
                       data: Dict[str, Any], now_ns: int) -> None:
        running = bool(data.get("running", True))
        eos = bool(data.get("eos", False))
        active = running and not eos and c.last_beat_ns is not None
        if active:
            age_s = (now_ns - c.last_beat_ns) / 1e9
            if age_s > float(self.stall_after_s):
                if not st.get("stall"):
                    st["stall"] = True
                    c.set_status(Status.STALLED,
                                 f"no buffer for {age_s:.2f}s")
                    _events.record(
                        "pipeline.stall",
                        f"{c.name}: no buffer for {age_s:.2f}s",
                        severity="warning", trace_id=c.last_trace_id,
                        stall_s=round(age_s, 3), **c.attrs)
                return  # stalled: skip the queue rule this tick
            if st.pop("stall", None):
                c.set_status(Status.OK, "buffers flowing again")
                _events.record("pipeline.recover",
                               f"{c.name}: buffers flowing again",
                               **c.attrs)
        elif st.pop("stall", None):
            # pipeline stopped or reached EOS: the verdict expires
            c.set_status(Status.OK, "stopped" if not running else "eos")
        # rule: queue high-watermark dwell → DEGRADED
        depth, bound = data.get("depth"), data.get("bound")
        if depth is None or not bound:
            return
        if active and depth >= bound:
            full_since = st.setdefault("full_since", now_ns)
            dwell_s = (now_ns - full_since) / 1e9
            if dwell_s > float(self.queue_dwell_s) and not st.get("full"):
                st["full"] = True
                c.set_status(Status.DEGRADED,
                             f"queue full ({depth}/{bound}) for "
                             f"{dwell_s:.2f}s")
                _events.record(
                    "pipeline.queue_full",
                    f"{c.name}: full ({depth}/{bound}) for {dwell_s:.2f}s",
                    severity="warning", trace_id=c.last_trace_id,
                    depth=depth, bound=bound, **c.attrs)
        else:
            st.pop("full_since", None)
            if st.pop("full", None):
                c.set_status(Status.OK, "queue draining")
                _events.record("pipeline.recover",
                               f"{c.name}: queue draining", **c.attrs)

    # rule: query reconnect storm → DEGRADED
    def _check_query(self, c: Component, st: Dict[str, Any],
                     now_ns: int) -> None:
        rc = c.counts.get("reconnect", 0)
        if "win_start" not in st:
            st["win_start"], st["win_rc"] = now_ns, rc
            return
        if (now_ns - st["win_start"]) / 1e9 < float(self.reconnect_window_s):
            return
        delta = rc - st["win_rc"]
        if delta >= int(self.reconnect_storm):
            if not st.get("storm"):
                st["storm"] = True
                # never mask an owner-set FAILED with the softer verdict
                if c.status < Status.DEGRADED:
                    c.set_status(
                        Status.DEGRADED,
                        f"{delta} reconnects in "
                        f"{self.reconnect_window_s:.0f}s")
                _events.record(
                    "query.reconnect_storm",
                    f"{c.name}: {delta} reconnects in "
                    f"{self.reconnect_window_s:.0f}s",
                    severity="warning", reconnects=delta, **c.attrs)
        elif st.pop("storm", None):
            if c.status == Status.DEGRADED:
                c.set_status(Status.OK, "reconnects settled")
            _events.record("query.recover",
                           f"{c.name}: reconnects settled", **c.attrs)
        st["win_start"], st["win_rc"] = now_ns, rc

    # rule: fleet instance missing its push heartbeat → STALLED
    # (obs/fleet.py registers one kind="fleet" component per pushing
    # instance; the probe reports the age of its last push and the ttl
    # derived from its advertised push interval)
    def _check_fleet(self, c: Component, st: Dict[str, Any],
                     data: Dict[str, Any]) -> None:
        age = float(data.get("push_age_s") or 0.0)
        ttl = float(data.get("ttl_s") or 0.0)
        if ttl > 0.0 and age > ttl:
            if not st.get("heartbeat"):
                st["heartbeat"] = True
                c.set_status(Status.STALLED,
                             f"no push for {age:.2f}s (ttl {ttl:.1f}s)")
                _events.record(
                    "fleet.stall",
                    f"{c.name}: no push for {age:.2f}s (ttl {ttl:.1f}s)",
                    severity="warning", push_age_s=round(age, 3),
                    **c.attrs)
        elif st.pop("heartbeat", None):
            c.set_status(Status.OK, "pushes resumed")
            _events.record("fleet.recover",
                           f"{c.name}: pushes resumed", **c.attrs)

    # rule: scheduler starvation storm → DEGRADED
    # (sched/engine.py registers one kind="sched" component per engine;
    # the probe reports its monotonically increasing relief count —
    # same windowed-delta shape as the reconnect-storm rule)
    def _check_sched(self, c: Component, st: Dict[str, Any],
                     data: Dict[str, Any], now_ns: int) -> None:
        reliefs = int(data.get("starvation_reliefs") or 0)
        if "win_start" not in st:
            st["win_start"], st["win_reliefs"] = now_ns, reliefs
            return
        if (now_ns - st["win_start"]) / 1e9 \
                < float(self.starvation_window_s):
            return
        delta = reliefs - st["win_reliefs"]
        # sched.* event literals live in the sched layer; import lazily
        # (no cycle: sched imports obs at module load, not vice versa)
        from ..sched import telemetry as _sched_tel
        if delta >= int(self.starvation_storm):
            if not st.get("storm"):
                st["storm"] = True
                if c.status < Status.DEGRADED:
                    c.set_status(
                        Status.DEGRADED,
                        f"{delta} starvation reliefs in "
                        f"{self.starvation_window_s:.0f}s")
                _sched_tel.event_starvation_storm(
                    c.name, delta, float(self.starvation_window_s),
                    **c.attrs)
        elif st.pop("storm", None):
            if c.status == Status.DEGRADED:
                c.set_status(Status.OK, "starvation reliefs settled")
            _sched_tel.event_starvation_recover(c.name, **c.attrs)
        st["win_start"], st["win_reliefs"] = now_ns, reliefs

    # rule: SLO burn-rate breach → DEGRADED
    # (obs/slo.py registers one kind="slo" component per objective
    # tenant; the probe is the registry's evaluate(), so the verdict
    # here is pure threshold bookkeeping)
    def _check_slo(self, c: Component, st: Dict[str, Any],
                   data: Dict[str, Any]) -> None:
        breached = bool(data.get("breached"))
        # slo.* event literals live in obs/slo.py; import lazily (slo
        # imports this module at load time, so top-level would cycle)
        from . import slo as _slo
        if breached:
            if not st.get("burn"):
                st["burn"] = True
                if c.status < Status.DEGRADED:
                    worst = data.get("worst_burn")
                    c.set_status(
                        Status.DEGRADED,
                        "SLO burn %.2fx budget (%s)"
                        % (worst if worst is not None else 0.0,
                           data.get("worst_objective")))
                _slo.event_burn_alert(c.name, data)
        elif st.pop("burn", None):
            if c.status == Status.DEGRADED:
                c.set_status(Status.OK, "burn back under budget")
            _slo.event_burn_recover(c.name, data)

    # rule: data-plane quality anomaly → DEGRADED
    # (obs/quality registers one kind="quality" component per tap; the
    # probe is the engine's evaluate(), so — like the slo rule — the
    # verdict here is pure transition bookkeeping)
    def _check_quality(self, c: Component, st: Dict[str, Any],
                       data: Dict[str, Any]) -> None:
        anomaly = data.get("anomaly")
        # quality.* event literals live in obs/quality; import lazily
        # (quality imports this module at load time, so top-level
        # would cycle)
        from . import quality as _quality
        if anomaly:
            if st.get("anomaly") != anomaly:
                st["anomaly"] = anomaly
                # alert first: the quality_anomaly diag cause should
                # win the trigger rate limit over the generic
                # watchdog_degraded cause set_status() fires next
                _quality.event_anomaly_alert(c.name, data)
                if c.status < Status.DEGRADED:
                    c.set_status(
                        Status.DEGRADED,
                        "quality anomaly: %s (%s)"
                        % (anomaly, data.get("detail") or "no detail"))
        elif st.pop("anomaly", None):
            if c.status == Status.DEGRADED:
                c.set_status(Status.OK, "quality anomaly cleared")
            _quality.event_anomaly_recover(c.name, data)

    # rule: serving request stuck in admission → STALLED
    def _check_serving(self, c: Component, st: Dict[str, Any],
                       data: Dict[str, Any]) -> None:
        wait = float(data.get("oldest_wait_s") or 0.0)
        if wait > float(self.admission_deadline_s):
            if not st.get("admission"):
                st["admission"] = True
                c.set_status(Status.STALLED,
                             f"request waiting {wait:.1f}s for a slot")
                _events.record(
                    "serving.admission_stall",
                    f"{c.name}: request waiting {wait:.1f}s for a slot",
                    severity="warning", oldest_wait_s=round(wait, 3),
                    **c.attrs)
        elif st.pop("admission", None):
            c.set_status(Status.OK, "admission moving")
            _events.record("serving.recover",
                           f"{c.name}: admission moving", **c.attrs)


# --------------------------------------------------------------------------- #
# Process-global registry + integration helpers
# --------------------------------------------------------------------------- #

#: off by default — the watchdog thread only ever starts after the
#: first registration while enabled (import starts nothing)
_REGISTRY = HealthRegistry(
    enabled=os.environ.get("NNSTPU_HEALTH", "") == "1")


def registry() -> HealthRegistry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY._enabled


def enable(**thresholds: Any) -> None:
    """Turn the health model on (``stall_after_s=``, ``queue_dwell_s=``,
    ``reconnect_storm=``, ``reconnect_window_s=``,
    ``admission_deadline_s=``, ``starvation_storm=``,
    ``starvation_window_s=``, ``interval_s=`` thresholds accepted).
    Like metrics/tracing: call BEFORE building pipelines/engines — the
    integration points register components at construction/start
    time."""
    _REGISTRY.enable(**thresholds)


def disable() -> None:
    _REGISTRY.disable()


def component(name: str, kind: str = "generic",
              probe: Optional[Callable] = None,
              attrs: Optional[Dict[str, Any]] = None):
    return _REGISTRY.component(name, kind, probe=probe, attrs=attrs)


def add_readiness(name: str, fn: Callable) -> None:
    _REGISTRY.add_readiness(name, fn)


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def readiness() -> Tuple[bool, Dict[str, bool]]:
    return _REGISTRY.readiness()


def check_now() -> None:
    _REGISTRY.check_now()


def element_probe(pipeline: Any, el: Any) -> Callable:
    """Weakref probe for one pipeline element: pipeline run/EOS state
    (the watchdog must not call a stopped pipeline stalled) merged with
    the element's own ``health_probe()`` dict (queue depth/bound) when
    it defines one. Returns None once either owner is collected."""
    wp, we = weakref.ref(pipeline), weakref.ref(el)

    def probe() -> Optional[Dict[str, Any]]:
        p, e = wp(), we()
        if p is None or e is None:
            return None
        d: Dict[str, Any] = {"running": p.running,
                             "eos": p.bus.wait_eos(0)}
        hp = getattr(e, "health_probe", None)
        if hp is not None:
            d.update(hp())
        return d

    return probe


def track_pipeline(pipeline: Any) -> None:
    """Pipeline.start hook (via obs/instrument.py): registers the
    readiness condition "pipeline PLAYING" for this pipeline. Weakref:
    a collected pipeline retires its condition instead of pinning it
    not-ready forever."""
    if not _REGISTRY._enabled:
        return
    wp = weakref.ref(pipeline)

    def cond() -> Optional[bool]:
        p = wp()
        return None if p is None else bool(p.running)

    _REGISTRY.add_readiness(f"pipeline:{pipeline.name}", cond)
