"""HTTP exposition endpoint: ``/metrics`` + ``/healthz`` + ``/readyz``
+ ``/debug``, stdlib only.

A daemon-threaded ``http.server`` serving the process-global (or a
given) ``MetricsRegistry`` in Prometheus text format — the scrape
target a production deployment points its collector at — plus the
health and debug surfaces:

  * ``GET /healthz``                 — liveness: aggregate component
    status from obs/health.py; 200 while ok/degraded, 503 on
    stalled/failing (always 200 "ok" while health is off)
  * ``GET /readyz``                  — readiness: 200 once every
    registered condition (pipeline PLAYING, engine warmed, query
    connected) holds; 503 otherwise (200 while health is off)
  * ``GET /debug/traces``            — JSON trace summaries, slowest
    first; ``?min_ms=<float>`` keeps only completed traces at least
    that slow
  * ``GET /debug/traces/<trace_id>`` — the full span tree of one trace
  * ``GET /debug/pipeline``          — live pipeline topology plus
    per-element span stats (the DOT-dump analog)
  * ``GET /debug/events``            — the flight-recorder event ring
    (obs/events.py), oldest first; ``?n=<int>`` keeps the newest N
  * ``GET /debug/fleet``             — per-instance fleet state when
    this process aggregates (obs/fleet.py); 503 otherwise
  * ``GET /debug/fleet/checkpoints`` — the local checkpoint daemon's
    session watermarks (fleet/checkpoint.py) plus, when aggregating,
    the fleet rollup: every instance's pushed watermarks and the
    tombstoned instances whose checkpoints still await a restore
  * ``GET /debug/profile``           — Chrome trace_event / Perfetto
    JSON timeline (obs/profile.py): host lanes per pipeline thread,
    device lanes per dispatch label, serving lanes + occupancy counter
  * ``GET /debug/profile/samples``   — the profiler's aggregated cost
    samples (the ``dump_samples()`` JSON shape), so a fleet collector
    gathers autotuner training data without exit files
  * ``GET /debug/slo``               — per-tenant cost attribution,
    goodput, objectives and burn rates (obs/slo.py); includes the
    fleet rollup when this process aggregates
  * ``GET /debug/quality``           — data-plane quality telemetry
    (obs/quality): per-tap tensor stats, drift scores, confidence
    aggregates and anomaly verdicts; includes the fleet rollup when
    this process aggregates
  * ``GET /debug``                   — the debug index: every route in
    this table, as JSON, derived from the dispatch table itself so it
    can never go stale
  * ``GET /debug/diag/critpath``     — per-tenant critical-path
    latency attribution (obs/diag): where each tenant's P99 goes,
    segment by segment; works from tracing alone, richer when the
    diag engine is enabled; ``?min_ms=<float>`` filters traces
  * ``GET /debug/bundles``           — incident debug bundles captured
    by the diag trigger engine (newest first) plus trigger stats;
    includes the fleet-wide bundle view when aggregating
  * ``GET /debug/bundles/<id>``      — one full bundle document (feed
    it to ``nns-diag`` for the offline waterfall); 503 while diag off
  * ``GET /debug/version``           — build identity: package
    version, jax version, device kind, python (also exported as the
    ``nnstpu_build_info`` gauge)
  * ``POST /fleet/push``             — snapshot-push ingestion for
    workers without a query wire; 503 unless aggregating

When fleet aggregation is enabled (``--obs-aggregate``), ``/metrics``
serves the merged fleet exposition (every instance's series with
``instance``/``role`` labels) and ``/healthz`` / ``/readyz`` the
worst-of-fleet rollups — checked per request, so no restart is needed
to switch roles.

All routes — GET and POST — live in ONE ``(method, path)`` dispatch
table; the 404 hint is derived from it, so a new endpoint can never be
forgotten from the hint, and adding one is a single table entry
regardless of method.

No new dependencies: ``ThreadingHTTPServer`` handles concurrent
scrapes and the GIL is irrelevant at scrape rates.

    from nnstreamer_tpu.obs import start_exporter
    exp = start_exporter(port=9464)   # also enables collection
    ...
    exp.close()

``port=0`` binds an ephemeral port (tests); the bound port is on
``exp.port`` and the full scrape URL on ``exp.url``.
"""

from __future__ import annotations

import errno
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from . import events as _events
from . import fleet as _fleet
from . import health as _health
from . import metrics as _metrics
from . import profile as _profile
from . import slo as _slo
from . import tracing as _tracing

__all__ = ["MetricsExporter", "start_exporter", "build_info"]

#: Prometheus text exposition content type (format 0.0.4)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def build_info() -> dict:
    """Code-identity snapshot: package version, jax version, device
    kind, python. Served at ``/debug/version``, embedded in every debug
    bundle, and exposed as the ``nnstpu_build_info`` gauge — the three
    places an incident reader asks "what code produced this?".
    Import-light and failure-tolerant (jax may be absent or mid-init)."""
    import platform

    from .. import __version__

    try:
        import jax

        jax_version = str(jax.__version__)
        dev = jax.devices()[0]
        device_kind = str(getattr(dev, "device_kind", None)
                          or getattr(dev, "platform", "unknown"))
    except Exception:
        jax_version = "unavailable"
        device_kind = "unknown"
    return {
        "version": __version__,
        "jax": jax_version,
        "device_kind": device_kind,
        "python": platform.python_version(),
    }


_BUILD_INFO_PUBLISHED = False


def _publish_build_info() -> None:
    """Register the constant-1 ``nnstpu_build_info`` gauge (Prometheus
    build-info idiom: the identity lives in the labels). Deferred to
    exporter start — probing jax for the device kind at import time
    would cost every non-serving import a device query."""
    global _BUILD_INFO_PUBLISHED
    if _BUILD_INFO_PUBLISHED:
        return
    _BUILD_INFO_PUBLISHED = True
    info = build_info()
    _metrics.registry().gauge(
        "nnstpu_build_info",
        "Build identity: constant 1; version/jax/device_kind labels "
        "carry the information",
        ("version", "jax", "device_kind"),
    ).labels(info["version"], info["jax"], info["device_kind"]).set(1.0)


class MetricsExporter:
    """Serves ``registry.exposition()`` at ``/metrics``, the health
    model at ``/healthz`` + ``/readyz``, and the debug surfaces, from
    a daemon thread."""

    def __init__(self, port: int = 9464, host: str = "127.0.0.1",
                 registry: Optional[_metrics.MetricsRegistry] = None):
        reg = registry if registry is not None else _metrics.registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                self._dispatch("GET")

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                self._dispatch("POST")

            def _dispatch(self, method):
                """One (method, path) table serves every verb — a new
                endpoint is one entry, GET or POST alike."""
                path, _, query = self.path.partition("?")
                handler = self._ROUTES.get((method, path))
                if handler is not None:
                    handler(self, query)
                    return
                for (m, prefix), ph in self._PREFIX_ROUTES:
                    if m == method and path.startswith(prefix):
                        ph(self, path[len(prefix):], query)
                        return
                self._reply(404, "text/plain", self._HINT)

            def _read_body(self):
                """Size-checked request body for POST handlers; replies
                413 and returns None when over MAX_PUSH_BYTES."""
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    n = -1
                if n < 0 or n > _fleet.MAX_PUSH_BYTES:
                    self._json(413, {"error": "push body too large"})
                    return None
                return self.rfile.read(n)

            # -- routes ------------------------------------------------ #
            # /metrics, /healthz, /readyz consult the fleet aggregator
            # per request: the process becomes (or stops being) the
            # fleet scrape target without an exporter restart
            def _get_metrics(self, query):
                agg = _fleet.aggregator()
                text = reg.exposition() if agg is None \
                    else agg.exposition(reg)
                self._reply(200, CONTENT_TYPE, text.encode("utf-8"))

            def _get_healthz(self, query):
                snap = _health.snapshot()
                agg = _fleet.aggregator()
                if agg is not None:
                    snap = agg.health_rollup(snap)
                # liveness: degraded still serves traffic; a stalled or
                # failing component flips the scrape to 503
                self._json(200 if snap["ok"] else 503, {
                    "status": snap["status"],
                    "health_enabled": _health.enabled(),
                    "metrics_enabled": reg.is_enabled,
                    "tracing_enabled": _tracing.enabled(),
                    "events_enabled": _events.enabled(),
                    "families": len(reg.names()),
                    "components": snap["components"],
                    **({"fleet": snap["fleet"]} if "fleet" in snap else {}),
                })

            def _get_readyz(self, query):
                ready, conds = _health.readiness()
                agg = _fleet.aggregator()
                if agg is not None:
                    ready, conds = agg.ready_rollup(ready, conds)
                self._json(200 if ready else 503, {
                    "ready": ready,
                    "health_enabled": _health.enabled(),
                    "conditions": conds,
                })

            def _get_fleet(self, query):
                agg = _fleet.aggregator()
                if agg is None:
                    self._json(503, {"error": "fleet aggregation is off "
                                     "(enable with --obs-aggregate)"})
                else:
                    self._json(200, agg.snapshot())

            def _get_traces(self, query):
                try:
                    min_ms = float(
                        parse_qs(query).get("min_ms", ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain",
                                b"min_ms must be a number")
                    return
                self._json(200, {
                    "tracing_enabled": _tracing.enabled(),
                    "traces": _tracing.store().summaries(min_ms),
                })

            def _get_trace(self, tid, query):
                tree = _tracing.store().tree(tid)
                if tree is None:
                    self._json(404, {"error": f"unknown trace {tid!r}"})
                else:
                    self._json(200, tree)

            def _get_pipeline(self, query):
                self._json(200, {
                    "pipelines": [_tracing.pipeline_topology(p)
                                  for p in _tracing.live_pipelines()],
                    "element_spans": _tracing.element_stats(),
                })

            def _get_events(self, query):
                try:
                    n = int(parse_qs(query).get("n", ["-1"])[0])
                except ValueError:
                    self._reply(400, "text/plain", b"n must be an int")
                    return
                ring = _events.ring()
                self._json(200, {
                    "events_enabled": _events.enabled(),
                    "dropped": ring.dropped,
                    "events": ring.snapshot(n if n >= 0 else None),
                })

            def _get_profile(self, query):
                # always 200: a valid (possibly sparse) trace with the
                # enable flags in otherData beats a 503 the viewer
                # cannot load
                self._json(200, _profile.perfetto_trace(
                    span_store=_tracing.store()))

            def _get_profile_samples(self, query):
                # same shape as dump_samples() writes to disk, so a
                # fleet aggregator collects autotuner training data
                # over HTTP instead of via --profile-dump exit files
                self._json(200, {
                    "version": 1,
                    "profile_enabled": _profile.enabled(),
                    "samples": _profile.samples(),
                })

            def _get_tune(self, query):
                from .. import tune as _tune

                agg = _fleet.aggregator()
                self._json(200, {
                    "enabled": _tune.enabled(),
                    "local": _tune.snapshot(),
                    "fleet": agg.tuned_view() if agg is not None
                    else None,
                })

            def _get_fleet_actions(self, query):
                # module-level _fleet is obs.fleet; the controller
                # package resolves lazily like _get_tune's import
                from .. import fleet as _fleetpkg

                agg = _fleet.aggregator()
                self._json(200, {
                    "enabled": _fleetpkg.enabled(),
                    "local": _fleetpkg.snapshot(),
                    "fleet": agg.actions_rollup() if agg is not None
                    else None,
                })

            def _get_fleet_checkpoints(self, query):
                # local watermarks ride the same hook the push doc
                # reads; the rollup needs this process to aggregate
                hook = _fleet.CHECKPOINT_HOOK
                agg = _fleet.aggregator()
                self._json(200, {
                    "local": None if hook is None else hook(),
                    "fleet": agg.checkpoints_rollup() if agg is not None
                    else None,
                })

            def _get_slo(self, query):
                snap = _slo.snapshot()
                agg = _fleet.aggregator()
                if agg is not None:
                    snap = {**snap, "fleet": agg.slo_rollup(
                        snap if snap.get("enabled") else None)}
                self._json(200, snap)

            def _get_quality(self, query):
                from . import quality as _quality

                snap = _quality.snapshot()
                agg = _fleet.aggregator()
                if agg is not None:
                    snap = {**snap,
                            "fleet": agg.quality_rollup()}
                self._json(200, snap)

            def _get_debug_index(self, query):
                # derived from the dispatch table, like the 404 hint:
                # an endpoint added there shows up here for free
                self._json(200, {
                    "routes": sorted(
                        f"{m} {p}" for m, p in self._ROUTES),
                    "prefix_routes": sorted(
                        f"{m} {p}<id>"
                        for (m, p), _ in self._PREFIX_ROUTES),
                })

            def _get_version(self, query):
                self._json(200, build_info())

            def _get_diag_critpath(self, query):
                # critpath is pure span-store analysis: it answers with
                # tracing alone even when the full diag engine (bundle
                # capture) is off — evidence should not need opting in
                from . import diag as _diag

                try:
                    min_ms = float(
                        parse_qs(query).get("min_ms", ["0"])[0])
                except ValueError:
                    self._reply(400, "text/plain",
                                b"min_ms must be a number")
                    return
                eng = _diag.DIAG_HOOK
                if eng is not None:
                    self._json(200, {"diag_enabled": True,
                                     **eng.critpath(min_ms)})
                else:
                    self._json(200, {
                        "diag_enabled": False,
                        "tracing_enabled": _tracing.enabled(),
                        **_diag.rollup(_tracing.store(), min_ms=min_ms),
                    })

            def _get_bundles(self, query):
                from . import diag as _diag

                eng = _diag.DIAG_HOOK
                agg = _fleet.aggregator()
                self._json(200, {
                    "diag_enabled": eng is not None,
                    "bundles": eng.bundles.list()
                    if eng is not None else [],
                    "triggers": dict(eng.triggers.stats)
                    if eng is not None else None,
                    "fleet": agg.diag_rollup() if agg is not None
                    else None,
                })

            def _get_bundle(self, bid, query):
                from . import diag as _diag

                eng = _diag.DIAG_HOOK
                if eng is None:
                    self._json(503, {"error": "diag is off (enable "
                                     "with --diag or NNSTPU_DIAG=1)"})
                    return
                doc = eng.bundles.get(bid)
                if doc is None:
                    self._json(404, {"error": f"unknown bundle {bid!r}"})
                else:
                    self._json(200, doc)

            def _post_fleet_push(self, query):
                body = self._read_body()
                if body is None:
                    return
                agg = _fleet.aggregator()
                if agg is None:
                    self._json(503, {"error": "this process is not a "
                                     "fleet aggregator (--obs-aggregate)"})
                    return
                try:
                    agg.ingest(json.loads(body or b"{}"), via="http")
                except (TypeError, ValueError) as e:
                    self._json(400, {"error": str(e)})
                    return
                # the ack carries the fleet's merged tuned configs so a
                # worker's very first push makes it warm (tune/ adopts
                # via obs/fleet.py TUNE_ADOPT_HOOK); None while no
                # instance has pushed tune data — the ack is then
                # byte-identical to pre-tune
                self._json(200, {"ok": True, "tune": agg.tuned_view()})

            #: THE route table — GET and POST share it, and the 404
            #: hint below derives from it, so adding an endpoint here
            #: is the whole registration
            _ROUTES = {
                ("GET", "/metrics"): _get_metrics,
                ("GET", "/healthz"): _get_healthz,
                ("GET", "/readyz"): _get_readyz,
                ("GET", "/debug/traces"): _get_traces,
                ("GET", "/debug/pipeline"): _get_pipeline,
                ("GET", "/debug/events"): _get_events,
                ("GET", "/debug/fleet"): _get_fleet,
                ("GET", "/debug/fleet/actions"): _get_fleet_actions,
                ("GET", "/debug/fleet/checkpoints"): _get_fleet_checkpoints,
                ("GET", "/debug/profile"): _get_profile,
                ("GET", "/debug/profile/samples"): _get_profile_samples,
                ("GET", "/debug/slo"): _get_slo,
                ("GET", "/debug/quality"): _get_quality,
                ("GET", "/debug"): _get_debug_index,
                ("GET", "/debug/tune"): _get_tune,
                ("GET", "/debug/diag/critpath"): _get_diag_critpath,
                ("GET", "/debug/bundles"): _get_bundles,
                ("GET", "/debug/version"): _get_version,
                ("POST", "/fleet/push"): _post_fleet_push,
            }
            _PREFIX_ROUTES = (
                (("GET", "/debug/traces/"), _get_trace),
                (("GET", "/debug/bundles/"), _get_bundle),
            )
            _HINT = ("not found (try " + ", ".join(sorted(
                [p if m == "GET" else f"{m} {p}" for m, p in _ROUTES]
                + [(p if m == "GET" else f"{m} {p}") + "<id>"
                   for (m, p), _ in _PREFIX_ROUTES]))
                + ")").encode("utf-8")

            def _json(self, code, obj):
                # default=str: span attrs are caller-provided (numpy
                # scalars, enums, ...) — render, never 500 a debug page
                self._reply(code, "application/json",
                            json.dumps(obj, default=str).encode("utf-8"))

            def _reply(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrape spam stays off stderr
                pass

        self.registry = reg
        _publish_build_info()
        try:
            self._server = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            if e.errno == errno.EADDRINUSE:
                raise RuntimeError(
                    f"metrics exporter: port {port} on {host} is already "
                    f"in use — pick a free port with --metrics-port (or "
                    f"port=0 for an ephemeral one)") from e
            raise
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._closed = False
        self._close_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"metrics-exporter:{self.port}")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        """Stop serving, join the thread, release the socket. Idempotent.
        The listening socket is closed only after the serve loop has
        been joined — closing it under ``serve_forever`` races select()
        on a dead fd; joining first makes the port free the moment
        close() returns."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._server.shutdown()
        self._thread.join(timeout=5)
        self._server.server_close()

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_exporter(port: int = 9464, host: str = "127.0.0.1",
                   registry: Optional[_metrics.MetricsRegistry] = None,
                   enable: bool = True) -> MetricsExporter:
    """Start the endpoint; by default also enables collection (a scrape
    target serving a disabled registry would be all zeros — surprising
    enough to be the wrong default)."""
    if enable:
        (registry if registry is not None else _metrics.registry()).enable()
    return MetricsExporter(port=port, host=host, registry=registry)
