"""HTTP exposition endpoint: ``/metrics`` + ``/healthz`` + ``/debug``,
stdlib only.

A daemon-threaded ``http.server`` serving the process-global (or a
given) ``MetricsRegistry`` in Prometheus text format — the scrape
target a production deployment points its collector at — plus the
trace-store debug surface:

  * ``GET /debug/traces``            — JSON trace summaries, slowest
    first; ``?min_ms=<float>`` keeps only completed traces at least
    that slow
  * ``GET /debug/traces/<trace_id>`` — the full span tree of one trace
  * ``GET /debug/pipeline``          — live pipeline topology plus
    per-element span stats (the DOT-dump analog)

No new dependencies: ``ThreadingHTTPServer`` handles concurrent
scrapes and the GIL is irrelevant at scrape rates.

    from nnstreamer_tpu.obs import start_exporter
    exp = start_exporter(port=9464)   # also enables collection
    ...
    exp.close()

``port=0`` binds an ephemeral port (tests); the bound port is on
``exp.port`` and the full scrape URL on ``exp.url``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from . import metrics as _metrics
from . import tracing as _tracing

__all__ = ["MetricsExporter", "start_exporter"]

#: Prometheus text exposition content type (format 0.0.4)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """Serves ``registry.exposition()`` at ``/metrics`` and a liveness
    JSON at ``/healthz`` from a daemon thread."""

    def __init__(self, port: int = 9464, host: str = "127.0.0.1",
                 registry: Optional[_metrics.MetricsRegistry] = None):
        reg = registry if registry is not None else _metrics.registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    body = reg.exposition().encode("utf-8")
                    self._reply(200, CONTENT_TYPE, body)
                elif path == "/healthz":
                    body = json.dumps({
                        "status": "ok",
                        "metrics_enabled": reg.is_enabled,
                        "tracing_enabled": _tracing.enabled(),
                        "families": len(reg.names()),
                    }).encode("utf-8")
                    self._reply(200, "application/json", body)
                elif path == "/debug/traces":
                    try:
                        min_ms = float(
                            parse_qs(query).get("min_ms", ["0"])[0])
                    except ValueError:
                        self._reply(400, "text/plain",
                                    b"min_ms must be a number")
                        return
                    self._json(200, {
                        "tracing_enabled": _tracing.enabled(),
                        "traces": _tracing.store().summaries(min_ms),
                    })
                elif path.startswith("/debug/traces/"):
                    tid = path[len("/debug/traces/"):]
                    tree = _tracing.store().tree(tid)
                    if tree is None:
                        self._json(404, {"error": f"unknown trace {tid!r}"})
                    else:
                        self._json(200, tree)
                elif path == "/debug/pipeline":
                    self._json(200, {
                        "pipelines": [_tracing.pipeline_topology(p)
                                      for p in _tracing.live_pipelines()],
                        "element_spans": _tracing.element_stats(),
                    })
                else:
                    self._reply(
                        404, "text/plain",
                        b"not found (try /metrics, /healthz, "
                        b"/debug/traces, /debug/pipeline)")

            def _json(self, code, obj):
                # default=str: span attrs are caller-provided (numpy
                # scalars, enums, ...) — render, never 500 a debug page
                self._reply(code, "application/json",
                            json.dumps(obj, default=str).encode("utf-8"))

            def _reply(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrape spam stays off stderr
                pass

        self.registry = reg
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"metrics-exporter:{self.port}")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_exporter(port: int = 9464, host: str = "127.0.0.1",
                   registry: Optional[_metrics.MetricsRegistry] = None,
                   enable: bool = True) -> MetricsExporter:
    """Start the endpoint; by default also enables collection (a scrape
    target serving a disabled registry would be all zeros — surprising
    enough to be the wrong default)."""
    if enable:
        (registry if registry is not None else _metrics.registry()).enable()
    return MetricsExporter(port=port, host=host, registry=registry)
