"""Element-chain instrumentation: wraps a pipeline's elements so every
buffer feeds the metrics registry — and, when tracing is on, the span
store.

One mechanism serves three consumers: ``Pipeline.start`` attaches it to
the process-global registry when metrics are enabled (always-on
telemetry for the exporter), ``PipelineTracer`` attaches it to a
private registry for a per-run report, and the tracing subsystem rides
the same wrap to open a ``pipeline.element`` span per chain call. All
metric consumers see the same series:

  * ``nnstpu_pipeline_buffers_total{element}`` — buffers entering chain
  * ``nnstpu_pipeline_proctime_seconds{element}`` — chain latency
    histogram (GstShark ``proctime`` analog)
  * ``nnstpu_pipeline_interlatency_seconds{element}`` — source-stamp to
    chain-entry latency (GstShark ``interlatency`` analog)
  * ``nnstpu_pipeline_errors_total{element}`` — chain errors/exceptions
  * ``nnstpu_pipeline_queue_depth{element}`` — queue occupancy, read at
    collection time (zero hot-path cost)

Span flow (obs/tracing.py): sources stamp a ``pipeline.buffer`` root
context onto ``Buffer.meta`` (unless the buffer already carries one —
a serversrc frame adopted off the wire keeps its remote trace), each
element chain opens a ``pipeline.element`` child and re-points the
buffer context at itself (so a linear chain renders as a linear tree),
and sink elements close the root. While a chain runs, its span is the
thread's *current* context, so nested work (an engine ``submit``, a
query send) joins the trace automatically.

When the health model (obs/health.py) is on, the same wrap stamps a
per-element heartbeat (``Component.beat()``) plus the buffer's trace
id per chain call, feeding the stall watchdog — and each element gets
a health component whose probe reports pipeline run/EOS state and any
element-specific ``health_probe()`` data (queue depth/bound).

The disabled fast path is structural: when neither metrics, tracing,
nor health are on at start time nothing here runs, element
``_chain_entry`` stays the plain class method, and the hot path pays
nothing (tests/test_obs.py pins this).

The profiler (obs/profile.py) deliberately does NOT ride this wrap: it
times chains through ``graph.element.PROFILE_CHAIN_HOOK`` (the chaos-
hook pattern — installed on ``profile.enable()``, None when off), so a
profile-only capture needs no pipeline restart and adds nothing to the
wrap above. Its host-lane element records are the tracing-off fallback
for ``/debug/profile``; with tracing on, the richer
``pipeline.element`` spans opened here are the host lanes.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from . import health as _health
from . import tracing as _tracing
from .metrics import MetricsRegistry, registry as _global_registry

__all__ = ["instrument_pipeline", "maybe_instrument_pipeline"]


def _families(reg: MetricsRegistry):
    return {
        "bufs": reg.counter(
            "nnstpu_pipeline_buffers_total",
            "Buffers entering each element's chain", ("element",)),
        "proc": reg.histogram(
            "nnstpu_pipeline_proctime_seconds",
            "Per-element chain processing time", ("element",)),
        "inter": reg.histogram(
            "nnstpu_pipeline_interlatency_seconds",
            "Latency from source stamp to element chain entry",
            ("element",)),
        "errs": reg.counter(
            "nnstpu_pipeline_errors_total",
            "Chain errors (exceptions or FlowReturn.ERROR) per element",
            ("element",)),
        "qdepth": reg.gauge(
            "nnstpu_pipeline_queue_depth",
            "Queue element occupancy (buffers)", ("element",)),
    }


def _wrapped_registries(el: Any) -> list:
    regs = el.__dict__.get("_obs_registries")
    if regs is None:
        regs = []
        el._obs_registries = regs
    return regs


def instrument_pipeline(pipeline: Any,
                        reg: Optional[MetricsRegistry] = None,
                        span_store: Optional["_tracing.SpanStore"] = None,
                        health: Optional["_health.HealthRegistry"] = None
                        ) -> None:
    """Wrap every element of ``pipeline`` to record into ``reg`` (the
    process-global registry by default); when ``span_store`` is given,
    open per-element spans into it; when ``health`` is given, register
    a component per element and heartbeat it per buffer. Idempotent
    per (element, registry): safe across restarts and combined tracer
    + exporter use (each consumer's wrap records to its own
    registry)."""
    from ..core.buffer import Buffer
    from ..graph.element import FlowReturn
    from ..graph.pipeline import Queue

    if reg is None:
        reg = _global_registry()
    fams = _families(reg)
    for el in pipeline.elements.values():
        regs = _wrapped_registries(el)
        if any(r is reg for r in regs):
            continue
        regs.append(reg)
        comp = None
        if health is not None:
            comp = health.component(
                f"element:{pipeline.name}:{el.name}", kind="element",
                probe=_health.element_probe(pipeline, el),
                attrs={"element": el.name, "pipeline": pipeline.name})
        if isinstance(el, Queue):
            # collection-time callback — queues' own locking protects
            # len() reads well enough for a monitoring sample
            fams["qdepth"].labels(el.name).set_function(
                lambda _el=el: len(_el._dq))
        if el.is_source:
            orig_create = getattr(el, "create", None)
            if orig_create is not None:
                def create_stamped(_orig=orig_create, _el=el,
                                   _spans=span_store, _comp=comp):
                    buf = _orig()
                    if buf is not None:
                        buf.meta.setdefault("trace_t0_ns",
                                            time.monotonic_ns())
                        if _spans is not None:
                            _tracing.stamp_buffer(buf, _spans, _el.name)
                        if _comp is not None:
                            _comp.beat()
                            ctx = buf.meta.get(_tracing.CTX_META_KEY)
                            if ctx is not None:
                                _comp.last_trace_id = ctx.trace_id
                    return buf

                el.create = create_stamped
            continue
        bufs = fams["bufs"].labels(el.name)
        proc = fams["proc"].labels(el.name)
        inter = fams["inter"].labels(el.name)
        errs = fams["errs"].labels(el.name)
        orig = el._chain_entry

        def timed_chain(pad, buf, _orig=orig, _bufs=bufs, _proc=proc,
                        _inter=inter, _errs=errs, _spans=span_store,
                        _comp=comp, _name=el.name, _sink=el.is_sink):
            is_buf = isinstance(buf, Buffer)
            t0 = buf.meta.get("trace_t0_ns") if is_buf else None
            start = time.monotonic_ns()
            if t0 is not None:
                _inter.observe((start - t0) / 1e9)
            _bufs.inc()
            if _comp is not None:
                # heartbeat + last-seen trace id: the watchdog's stall
                # rule reads the beat age; its verdict event carries
                # the trace that stopped moving
                _comp.beat()
                if is_buf:
                    hctx = buf.meta.get(_tracing.CTX_META_KEY)
                    if hctx is not None:
                        _comp.last_trace_id = hctx.trace_id
            span = None
            token = None
            if _spans is not None and is_buf:
                parent = buf.meta.get(_tracing.CTX_META_KEY)
                if parent is not None:
                    span = _spans.start_span(
                        "pipeline.element", parent=parent,
                        attrs={"element": _name})
                    if span.recording:
                        # linear chains render as linear trees: the
                        # next element parents onto THIS span
                        buf.meta[_tracing.CTX_META_KEY] = span.context
                        token = _tracing._set_current(span.context)
                    else:
                        span = None
            try:
                ret = _orig(pad, buf)
            except Exception:
                _errs.inc()
                if span is not None:
                    span.set_attribute("error", True)
                raise
            finally:
                if token is not None:
                    _tracing._reset_current(token)
                if span is not None:
                    span.end()
                if _sink and is_buf:
                    # the buffer reached a sink: close its root span
                    # (idempotent — tee'd buffers hit several sinks)
                    root = buf.meta.get(_tracing.ROOT_META_KEY)
                    if root is not None:
                        root.end()
            _proc.observe((time.monotonic_ns() - start) / 1e9)
            if ret is FlowReturn.ERROR:
                _errs.inc()
            return ret

        el._chain_entry = timed_chain


def maybe_instrument_pipeline(pipeline: Any) -> None:
    """Pipeline.start hook: attach to the global registry iff metrics,
    tracing, OR health are enabled — the structural no-op fast path
    when none are. (Metrics recording into a disabled registry is
    itself a flag-check no-op, so a tracing- or health-only run costs
    no metric state.) Also registers the pipeline for /debug/pipeline
    topology — a WeakSet add, unconditionally cheap."""
    _tracing.register_pipeline(pipeline)
    spans = _tracing.store() if _tracing.enabled() else None
    health = _health.registry() if _health.enabled() else None
    if health is not None:
        # readiness: "pipeline PLAYING" flips true at the end of start
        _health.track_pipeline(pipeline)
    if _global_registry().is_enabled or spans is not None \
            or health is not None:
        instrument_pipeline(pipeline, span_store=spans, health=health)
