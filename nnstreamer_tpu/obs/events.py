"""Flight recorder: a bounded ring of structured runtime events.

The third observability pillar (after metrics and tracing): when a
long-running pipeline wedges or dies, the metrics say *how fast* it
was and the traces say *where one request went* — this ring says *what
happened last*: element errors, pipeline state changes, query
reconnects, admission rejections, watchdog verdicts (obs/health.py),
and warning/error log records bridged from core/log.py's ``nns_tpu``
logger tree.

Each event is a plain dict::

    {"seq": 17, "ts": 1722900000.123, "type": "pipeline.stall",
     "severity": "warning", "message": "sink stopped consuming",
     "trace_id": "ab12..." | None, "span_id": "cd34..." | None,
     "attrs": {...}}

``trace_id``/``span_id`` come from obs/tracing.py's current-context
contextvar at record time, so an event emitted inside an instrumented
element chain or a traced request correlates with its /debug/traces
entry for free. Event *types* are literal lowercase ``<layer>.<event>``
names (linted by scripts/check_metric_names.py next to metric and span
names).

Same contract as metrics/tracing: **off by default, one flag check
while off** — ``record()`` is a boolean test and a return. ``enable()``
(or ``NNSTPU_EVENTS=1``) additionally installs two passive taps:

  * a logging.Handler on the ``nns_tpu`` logger bridging WARNING+
    records into the ring (``core.log`` events);
  * a ``threading.excepthook`` wrapper that dumps the ring to stderr
    when a pipeline-owned thread (source loop, queue worker, query
    reader/server, serving drain) dies on an unhandled exception —
    the crash context a daemon thread would otherwise take with it.

Exposition: ``GET /debug/events`` on the obs exporter (``?n=`` limits
to the newest N); ``nns-launch --events-dump PATH`` writes the ring as
JSON lines at exit (``-`` for the stderr text dump).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import tracing as _tracing

__all__ = [
    "EventRing", "disable", "dump", "dump_jsonl", "enable", "enabled",
    "record", "ring",
]

#: default ring capacity — bounded memory however long the run
DEFAULT_CAPACITY = 512

#: thread-name prefixes owned by pipeline machinery: an unhandled
#: exception on one of these is a pipeline crash worth a ring dump
#: (src loops, queue/batch workers, query reader/server threads,
#: serversink drain, the health watchdog itself)
_PIPELINE_THREAD_PREFIXES = (
    "src:", "q:", "batch:", "qsink:", "qclient-reader:", "qsrv-",
    "obs-health-watchdog", "obs-fleet-push",
)


class EventRing:
    """Lock-protected bounded event journal. ``record`` is the only
    hot-path entry and costs one flag check when disabled."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = False):
        self._lock = threading.Lock()
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=int(capacity))  # guarded-by: _lock
        self._enabled = bool(enabled)
        self._seq = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock

    # -- enable/disable ------------------------------------------------ #
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def capacity(self) -> int:
        return self._dq.maxlen or 0

    @property
    def dropped(self) -> int:
        return self._dropped

    def reset(self) -> None:
        with self._lock:
            self._dq.clear()
            self._seq = 0
            self._dropped = 0

    # -- recording ----------------------------------------------------- #
    def record(self, etype: str, message: str, severity: str = "info",
               trace_id: Optional[str] = None, **attrs: Any) -> None:
        """Append one event; the flag check is the whole disabled cost.

        ``trace_id`` overrides the contextvar lookup — watchdog verdicts
        pass the stalled component's *last seen* trace id because the
        watchdog thread itself never runs inside a traced chain."""
        if not self._enabled:
            return
        ctx = _tracing.current_context()
        ev = {
            "seq": 0,  # assigned under the lock
            # both clock domains: wall ("ts") correlates events across
            # hosts in a fleet bundle, monotonic ("mono_ns") orders and
            # measures them locally without clock-step ambiguity
            "ts": time.time(),
            "mono_ns": time.monotonic_ns(),
            "type": etype,
            "severity": severity,
            "message": message,
            "trace_id": trace_id if trace_id is not None
            else (ctx.trace_id if ctx is not None else None),
            "span_id": ctx.span_id if ctx is not None else None,
            "attrs": attrs,
        }
        with self._lock:
            if len(self._dq) == self._dq.maxlen:
                self._dropped += 1
            ev["seq"] = self._seq
            self._seq += 1
            self._dq.append(ev)

    # -- queries ------------------------------------------------------- #
    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-last copy; ``limit`` keeps only the newest N."""
        with self._lock:
            out = list(self._dq)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)


# --------------------------------------------------------------------------- #
# Process-global ring + taps
# --------------------------------------------------------------------------- #

_RING = EventRing(enabled=os.environ.get("NNSTPU_EVENTS", "") == "1")


def ring() -> EventRing:
    return _RING


def enabled() -> bool:
    return _RING._enabled


def record(etype: str, message: str, severity: str = "info",
           trace_id: Optional[str] = None, **attrs: Any) -> None:
    """Module-level recorder — THE call every emit site uses. The
    naming lint greps these call sites: keep the event type a literal
    lowercase ``<layer>.<event>`` string."""
    _RING.record(etype, message, severity, trace_id=trace_id, **attrs)


class _LogBridge(logging.Handler):
    """WARNING+ records from the ``nns_tpu`` logger tree become
    ``core.log`` events — the "what was the code complaining about"
    half of a post-mortem dump."""

    def emit(self, rec: logging.LogRecord) -> None:
        try:
            record("core.log", rec.getMessage(),
                   severity=rec.levelname.lower(), logger=rec.name)
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


_bridge: Optional[_LogBridge] = None
_prev_excepthook = None


def _excepthook(args) -> None:
    """threading.excepthook wrapper: a pipeline-owned thread dying on
    an unhandled exception records a ``pipeline.crash`` event and dumps
    the ring to stderr (daemon threads otherwise vanish silently)."""
    t = args.thread
    name = t.name if t is not None else ""
    if any(name.startswith(p) for p in _PIPELINE_THREAD_PREFIXES):
        record("pipeline.crash",
               f"unhandled {args.exc_type.__name__} in thread {name}: "
               f"{args.exc_value}", severity="error", thread=name)
        dump(sys.stderr)
    if _prev_excepthook is not None:
        _prev_excepthook(args)


def enable(capacity: Optional[int] = None) -> None:
    """Turn the flight recorder on and install the log bridge + thread
    excepthook taps. Idempotent. ``capacity`` resizes (and clears) the
    ring."""
    global _bridge, _prev_excepthook
    if capacity is not None and capacity != _RING.capacity:
        with _RING._lock:
            _RING._dq = deque(_RING._dq, maxlen=int(capacity))
    _RING.enable()
    if _bridge is None:
        _bridge = _LogBridge()
        _bridge.setLevel(logging.WARNING)
        logging.getLogger("nns_tpu").addHandler(_bridge)
    if _prev_excepthook is None:
        _prev_excepthook = threading.excepthook
        threading.excepthook = _excepthook


def disable() -> None:
    """Turn recording off and remove the taps (restores the previous
    threading.excepthook)."""
    global _bridge, _prev_excepthook
    _RING.disable()
    if _bridge is not None:
        logging.getLogger("nns_tpu").removeHandler(_bridge)
        _bridge = None
    if _prev_excepthook is not None:
        threading.excepthook = _prev_excepthook
        _prev_excepthook = None


# -- dumps ------------------------------------------------------------------ #

def dump(fp=None) -> None:
    """Human-readable dump, newest last (default: stderr)."""
    fp = fp or sys.stderr
    events = _RING.snapshot()
    print(f"-- flight recorder: {len(events)} event(s), "
          f"{_RING.dropped} dropped --", file=fp)
    for ev in events:
        ts = time.strftime("%H:%M:%S", time.localtime(ev["ts"]))
        extra = " ".join(f"{k}={v!r}" for k, v in ev["attrs"].items())
        tid = f" trace={ev['trace_id']}" if ev["trace_id"] else ""
        print(f"[{ts}] {ev['severity'].upper():<7} {ev['type']:<24} "
              f"{ev['message']}{(' ' + extra) if extra else ''}{tid}",
              file=fp)


def dump_jsonl(path: str) -> None:
    """Write the ring as JSON lines (one event per line)."""
    with open(path, "w", encoding="utf-8") as fh:
        for ev in _RING.snapshot():
            fh.write(json.dumps(ev, default=str) + "\n")
