"""Data-plane observability: tensor stats, drift, and model confidence.

Every other observability pillar (metrics, tracing, health, profile,
slo, diag, fleet) watches the *machinery* — queues, latencies, device
seconds.  This one watches the *data*: the tensors flowing through the
pipeline and the logits coming out of the model.  Three layers:

1. **Streaming tensor statistics** — per-tap Welford mean/variance,
   min/max, NaN/Inf/zero counts, a log-bucket magnitude sketch, and
   the inter-frame delta magnitude, computed on host from buffers that
   are ALREADY host-resident (a device-resident tensor is never pulled
   back just to be looked at).  Taps: element chain (``chain:<name>``,
   the buffer entering each sink pad), filter output
   (``filter:<name>``), decoder output (``decoder:<name>``), plus
   model-confidence telemetry (logit entropy, top-1 probability,
   top-2 margin) recorded per tenant/session at the LM retire path
   (``lm:<engine>``).

2. **Drift detection** — ``nns-launch --quality-record`` freezes each
   tap's sketch to a JSON :class:`~.drift.Baseline`; a later run with
   ``baseline=<path>`` scores every observed frame's sketch against it
   (PSI) through :class:`~.drift.DriftWindows` — fast/slow windows,
   breach requires both, injectable clock (the obs/slo burn pattern).

3. **Reaction wiring** — NaN-storm (NaN/Inf in >= ``nan_storm``
   consecutive frames) and dead-output (constant/all-zero for
   >= ``dead_frames`` frames) rules, plus a drift breach, surface as a
   ``kind="quality"`` health component per tap; the watchdog flips it
   DEGRADED, :func:`event_anomaly_alert` fires ``quality.anomaly`` and
   obs/diag's ``quality_anomaly`` trigger auto-captures a debug bundle
   with the offending tap's stats frozen in a ``quality`` stanza.
   ``nnstpu_quality_*`` metrics, ``GET /debug/quality``, the fleet
   push-doc ``quality`` field, and a Perfetto quality lane (pid 7)
   make it all visible.

Zero-overhead-when-off: :data:`QUALITY_HOOK` is a module global that
stays ``None`` until :func:`enable` — every tap site pays one module
attribute load plus a ``None`` check (the chaos/profile/slo contract,
pinned by an inspect test).  Set ``NNSTPU_QUALITY=1`` (or a SPEC
string, e.g. ``NNSTPU_QUALITY=taps=chain+filter,nan_storm=2``) to
enable at import; ``nns-launch --quality[=SPEC]`` does the same.

Tap-label cardinality is bounded: at most ``max_taps`` taps are kept
(overflow folds into ``_overflow``), confidence sessions are LRU-capped.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import events as _events
from .. import health as _health
from .. import metrics as _metrics
from .drift import (Baseline, DriftWindows, DEFAULT_FAST_WINDOW_S,
                    DEFAULT_PSI_THRESHOLD, DEFAULT_SLOW_WINDOW_S)
from .stats import TapStats, psi as _psi

__all__ = [
    "QualityEngine",
    "QUALITY_HOOK",
    "enable",
    "disable",
    "enabled",
    "engine",
    "snapshot",
    "push_data",
    "trace_points",
    "bundle_data",
    "report",
    "save_baseline",
    "parse_quality_spec",
    "event_anomaly_alert",
    "event_anomaly_recover",
    "Baseline",
    "DriftWindows",
    "TapStats",
]

# Defaults -----------------------------------------------------------------

TAP_KINDS = ("chain", "filter", "decoder", "lm")
DEFAULT_NAN_STORM = 3
DEFAULT_DEAD_FRAMES = 8
DEFAULT_MAX_TAPS = 64
# 2k stride-samples bound every tap to thumbnail cost regardless of frame
# size; the anomaly signals (NaN storms poison whole tensors, dead output
# is all-constant) and the exponent sketch are insensitive to the cap,
# and the <=5% overhead gate (bench quality_overhead_ratio) rides on it
DEFAULT_SAMPLE_CAP = 2048
OVERFLOW_TAP = "_overflow"
ANOMALY_KINDS = ("nan_storm", "dead_output", "drift")
_TRACE_CAP = 4096
_SESSION_LIMIT = 256

# Hook ---------------------------------------------------------------------
# None unless enable() was called; tap sites load the module attribute and
# None-check before every use so a disabled run pays nothing.

#: Consumed by graph.element.Pad.push, elements/filter + decoder chains,
#: and the serving LMEngine admit/retire paths.
QUALITY_HOOK: Optional["QualityEngine"] = None


class _Tap:
    """Mutable per-tap state. Guarded by the engine lock."""

    __slots__ = ("name", "stats", "seen", "skipped_device", "consec_nan",
                 "consec_dead", "anomaly", "detail", "drift",
                 "drift_breached", "last_psi")

    def __init__(self, name: str, sample_cap: int,
                 drift: Optional[DriftWindows]) -> None:
        self.name = name
        self.stats = TapStats(sample_cap)
        self.seen = 0
        self.skipped_device = 0
        self.consec_nan = 0
        self.consec_dead = 0
        self.anomaly: Optional[str] = None
        self.detail = ""
        self.drift = drift
        self.drift_breached = False
        self.last_psi: Optional[float] = None


class _ConfAgg:
    """Welford moments over one tenant's/session's confidence stream."""

    __slots__ = ("entropy", "top1", "margin")

    def __init__(self) -> None:
        from .stats import Welford
        self.entropy = Welford()
        self.top1 = Welford()
        self.margin = Welford()

    def add(self, entropy: float, top1: float, margin: float) -> None:
        self.entropy.add(entropy)
        self.top1.add(top1)
        self.margin.add(margin)

    def as_dict(self) -> Dict[str, Any]:
        return {"n": self.entropy.n,
                "entropy": self.entropy.as_dict(),
                "top1": self.top1.as_dict(),
                "margin": self.margin.as_dict()}


class QualityEngine:
    """Per-tap tensor statistics, drift scoring, and anomaly rules.

    One instance is installed into :data:`QUALITY_HOOK` by
    :func:`enable`.  Observation methods are thread-safe; metric
    emission happens outside the lock; device-resident tensors are
    counted as skipped, never copied back.
    """

    def __init__(self, *, taps: Sequence[str] = TAP_KINDS,
                 every: int = 1,
                 baseline: Optional[Baseline] = None,
                 psi_threshold: float = DEFAULT_PSI_THRESHOLD,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 nan_storm: int = DEFAULT_NAN_STORM,
                 dead_frames: int = DEFAULT_DEAD_FRAMES,
                 max_taps: int = DEFAULT_MAX_TAPS,
                 sample_cap: int = DEFAULT_SAMPLE_CAP,
                 clock: Callable[[], float] = time.monotonic) -> None:
        bad = [t for t in taps if t not in TAP_KINDS]
        if bad:
            raise ValueError(f"unknown tap kinds {bad} (one of {TAP_KINDS})")
        if every < 1:
            raise ValueError("every must be >= 1")
        if nan_storm < 1 or dead_frames < 1:
            raise ValueError("nan_storm and dead_frames must be >= 1")
        if max_taps < 1:
            raise ValueError("max_taps must be >= 1")
        self.taps_enabled = frozenset(taps)
        self.every = int(every)
        self.baseline = baseline
        self.psi_threshold = float(psi_threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.nan_storm = int(nan_storm)
        self.dead_frames = int(dead_frames)
        self.max_taps = int(max_taps)
        self.sample_cap = int(sample_cap)
        self.clock = clock
        self._lock = threading.Lock()
        # Guarded by _lock:
        self._taps: Dict[str, _Tap] = {}
        self._conf_tenants: Dict[str, _ConfAgg] = {}
        self._conf_sessions: "OrderedDict[str, _ConfAgg]" = OrderedDict()
        self._trace: deque = deque(maxlen=_TRACE_CAP)
        self._register_metrics()

    # -- metrics ----------------------------------------------------------

    def _register_metrics(self) -> None:
        reg = _metrics.registry()
        self._m_frames = reg.counter(
            "nnstpu_quality_frames_total",
            "Frames observed by the data-plane quality layer per tap",
            labelnames=("tap",))
        self._m_anoms = reg.counter(
            "nnstpu_quality_anomalies_total",
            "Data-plane anomalies detected per tap by kind",
            labelnames=("tap", "kind"))
        self._m_psi = reg.gauge(
            "nnstpu_quality_drift_psi",
            "Mean population-stability-index vs baseline per tap/window",
            labelnames=("tap", "window"))

    # -- taps (lock held) -------------------------------------------------

    def _tap(self, name: str) -> Tuple[_Tap, bool]:
        t = self._taps.get(name)
        if t is not None:
            return t, False
        if len(self._taps) >= self.max_taps:
            name = OVERFLOW_TAP
            t = self._taps.get(name)
            if t is not None:
                return t, False
        drift = None
        if self.baseline is not None \
                and self.baseline.sketch_for(name) is not None:
            drift = DriftWindows(
                fast_window_s=self.fast_window_s,
                slow_window_s=self.slow_window_s,
                psi_threshold=self.psi_threshold, clock=self.clock)
        t = _Tap(name, self.sample_cap, drift)
        self._taps[name] = t
        return t, True

    # -- observation hooks --------------------------------------------------

    def observe_chain(self, element: str, buf: Any) -> None:
        """Buffer entering ``element``'s sink pad (graph.element.Pad)."""
        if "chain" in self.taps_enabled:
            self._observe(f"chain:{element}", buf)

    def observe_filter(self, element: str, buf: Any) -> None:
        """A tensor_filter's output buffer, pre-decoration."""
        if "filter" in self.taps_enabled:
            self._observe(f"filter:{element}", buf)

    def observe_decoder(self, element: str, buf: Any) -> None:
        """A tensor_decoder's decoded output buffer."""
        if "decoder" in self.taps_enabled:
            self._observe(f"decoder:{element}", buf)

    def _observe(self, tap: str, buf: Any) -> None:
        # primary host-resident memory only: peeking at _host (instead
        # of calling .host()) guarantees the tap never forces a D2H
        # copy — device-resident frames are counted as skipped
        mem = None
        for m in getattr(buf, "memories", ()):
            if m._host is not None:
                mem = m
                break
        emit_anom: Optional[str] = None
        with self._lock:
            t, created = self._tap(tap)
            name = t.name
            t.seen += 1
            if mem is None:
                t.skipped_device += 1
            elif self.every == 1 or (t.seen - 1) % self.every == 0:
                info = t.stats.observe(mem._host)
                if info["nan_frame"]:
                    t.consec_nan += 1
                    t.consec_dead = 0
                elif info["dead"]:
                    t.consec_dead += 1
                    t.consec_nan = 0
                else:
                    t.consec_nan = 0
                    t.consec_dead = 0
                anomaly = None
                if t.consec_nan >= self.nan_storm:
                    anomaly = "nan_storm"
                    detail = ("%d consecutive frames with NaN/Inf "
                              "(%d non-finite values total)"
                              % (t.consec_nan,
                                 t.stats.nan_count + t.stats.inf_count))
                elif t.consec_dead >= self.dead_frames:
                    anomaly = "dead_output"
                    detail = ("%d consecutive constant frames "
                              "(last mean %.6g)"
                              % (t.consec_dead, info["mean"]))
                if anomaly != t.anomaly:
                    if anomaly is not None:
                        emit_anom = anomaly
                        t.detail = detail
                    else:
                        t.detail = ""
                    t.anomaly = anomaly
                psi_score = None
                if t.drift is not None:
                    ref = self.baseline.sketch_for(name)
                    psi_score = _psi(ref, info["sketch"].as_dict())
                    t.drift.add(psi_score)
                    t.last_psi = psi_score
                self._trace.append({
                    "t_ns": time.monotonic_ns(), "tap": name,
                    "mean": info["mean"] if info["mean"] == info["mean"]
                    else 0.0,
                    "psi": psi_score if psi_score is not None else 0.0,
                    "nan": t.stats.nan_count + t.stats.inf_count,
                })
        if created:
            self._ensure_component(name)
        self._m_frames.labels(name).inc()
        if emit_anom is not None:
            self._m_anoms.labels(name, emit_anom).inc()

    def record_confidence(self, engine: str, tenant: str,
                          session: Optional[str], entropy: float,
                          top1: float, margin: float) -> None:
        """One retired LM request's first-token confidence signals."""
        if "lm" not in self.taps_enabled:
            return
        tap = f"lm:{engine}"
        with self._lock:
            agg = self._conf_tenants.get(tenant)
            if agg is None:
                if len(self._conf_tenants) >= self.max_taps:
                    tenant = OVERFLOW_TAP
                agg = self._conf_tenants.setdefault(tenant, _ConfAgg())
            agg.add(entropy, top1, margin)
            if session is not None:
                sagg = self._conf_sessions.get(session)
                if sagg is None:
                    sagg = self._conf_sessions[session] = _ConfAgg()
                sagg.add(entropy, top1, margin)
                self._conf_sessions.move_to_end(session)
                while len(self._conf_sessions) > _SESSION_LIMIT:
                    self._conf_sessions.popitem(last=False)
            self._trace.append({
                "t_ns": time.monotonic_ns(), "tap": tap,
                "mean": entropy, "psi": 0.0, "nan": 0,
            })
        self._m_frames.labels(tap).inc()

    # -- anomaly evaluation + health ----------------------------------------

    def _ensure_component(self, tap: str) -> None:
        ref = weakref.ref(self)

        def probe() -> Optional[Dict[str, Any]]:
            eng = ref()
            if eng is None or _ENGINE is not eng:
                return None  # retire the component
            return eng.evaluate(tap)

        _health.component(f"quality:{tap}", kind="quality", probe=probe,
                          attrs={"tap": tap})

    def evaluate(self, tap: str,
                 now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One tap's anomaly verdict: the NaN-storm/dead-output state
        machine plus (when a baseline is loaded) the multi-window
        drift burn.  This is the health probe payload."""
        drift_edge = False
        with self._lock:
            t = self._taps.get(tap)
            if t is None:
                return None
            anomaly, detail = t.anomaly, t.detail
            drift_eval = t.drift.evaluate(now) if t.drift is not None \
                else None
            if drift_eval is not None:
                breached = drift_eval["breached"]
                if breached and anomaly is None:
                    anomaly = "drift"
                    w = drift_eval["windows"]
                    detail = ("PSI fast=%.3f slow=%.3f over "
                              "threshold %.2f"
                              % (w["fast"]["mean_psi"],
                                 w["slow"]["mean_psi"],
                                 drift_eval["psi_threshold"]))
                if breached and not t.drift_breached:
                    drift_edge = True
                t.drift_breached = breached
            data = {
                "tap": tap,
                "anomaly": anomaly,
                "detail": detail,
                "frames": t.stats.frames,
                "nan": t.stats.nan_count + t.stats.inf_count,
                "psi": t.last_psi,
                "drift": drift_eval,
            }
        if drift_eval is not None:
            w = drift_eval["windows"]
            self._m_psi.labels(tap, "fast").set(w["fast"]["mean_psi"])
            self._m_psi.labels(tap, "slow").set(w["slow"]["mean_psi"])
        if drift_edge:
            self._m_anoms.labels(tap, "drift").inc()
        return data

    # -- export -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            names = list(self._taps)
            rows: Dict[str, Dict[str, Any]] = {}
            for name in names:
                t = self._taps[name]
                rows[name] = {
                    **t.stats.snapshot(),
                    "seen": t.seen,
                    "skipped_device": t.skipped_device,
                    "anomaly": t.anomaly,
                    "detail": t.detail,
                    "psi": t.last_psi,
                }
            conf = {
                "tenants": {k: v.as_dict()
                            for (k, v) in self._conf_tenants.items()},
                "sessions": {k: v.as_dict()
                             for (k, v) in self._conf_sessions.items()},
            }
        for name in names:
            # Health may have been enabled after the tap appeared —
            # re-registering is a cheap get-or-create.
            self._ensure_component(name)
            ev = self.evaluate(name)
            if ev is not None:
                rows[name]["anomaly"] = ev["anomaly"]
                rows[name]["detail"] = ev["detail"]
                rows[name]["drift"] = ev["drift"]
        return {
            "enabled": True,
            "taps_enabled": sorted(self.taps_enabled),
            "every": self.every,
            "baseline": self.baseline is not None,
            "psi_threshold": self.psi_threshold,
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "rules": {"nan_storm": self.nan_storm,
                      "dead_frames": self.dead_frames},
            "taps": rows,
            "confidence": conf,
        }

    def anomalies(self) -> Dict[str, Dict[str, Any]]:
        """Currently anomalous taps: ``{tap: {kind, detail}}``."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            names = list(self._taps)
        for name in names:
            ev = self.evaluate(name)
            if ev is not None and ev["anomaly"] is not None:
                out[name] = {"kind": ev["anomaly"],
                             "detail": ev["detail"]}
        return out

    def push_data(self) -> Dict[str, Any]:
        """Compact per-tap summary for the fleet push doc."""
        anomalies = self.anomalies()
        with self._lock:
            taps = {
                name: {
                    "frames": t.stats.frames,
                    "nan": t.stats.nan_count + t.stats.inf_count,
                    "psi": t.last_psi,
                }
                for (name, t) in self._taps.items()
            }
        return {"taps": taps, "anomalies": anomalies}

    def bundle_data(self) -> Dict[str, Any]:
        """Debug-bundle stanza: the full snapshot with the offending
        (anomalous) taps called out up front."""
        snap = self.snapshot()
        snap["anomalies"] = self.anomalies()
        return snap

    def trace_points(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._trace)

    def save_baseline(self, path: str) -> Baseline:
        """Freeze every tap's cumulative sketch as a drift baseline."""
        with self._lock:
            taps = {name: t.stats.sketch.as_dict()
                    for (name, t) in self._taps.items()
                    if t.stats.frames}
            meta = {"frames": sum(t.stats.frames
                                  for t in self._taps.values()),
                    "psi_threshold": self.psi_threshold}
        base = Baseline(taps, meta=meta)
        base.save(path)
        _events.record("quality.baseline_saved",
                       f"drift baseline frozen to {path} "
                       f"({len(taps)} taps)", path=path, taps=len(taps))
        return base

    def report(self) -> str:
        snap = self.snapshot()
        lines = ["quality: data-plane observation"]
        for (name, row) in sorted(snap["taps"].items()):
            mom = row["moments"]
            psi_txt = "" if row.get("psi") is None \
                else " psi=%.3f" % row["psi"]
            lines.append(
                "  %-24s frames=%d mean=%.6g std=%.3g nan=%d zero=%d%s"
                % (name, row["frames"], mom["mean"],
                   mom["var"] ** 0.5, row["nan"], row["zero"], psi_txt))
            if row.get("anomaly"):
                lines.append("  %-24s ANOMALY %s: %s"
                             % ("", row["anomaly"], row["detail"]))
        for (tenant, agg) in sorted(snap["confidence"]["tenants"].items()):
            lines.append(
                "  lm[%s]: n=%d entropy=%.3f top1=%.3f margin=%.3f"
                % (tenant, agg["n"], agg["entropy"]["mean"],
                   agg["top1"]["mean"], agg["margin"]["mean"]))
        return "\n".join(lines)


# Module API ---------------------------------------------------------------

_ENGINE: Optional[QualityEngine] = None


def engine() -> Optional[QualityEngine]:
    return _ENGINE


def enabled() -> bool:
    return _ENGINE is not None


def parse_quality_spec(text: str) -> Dict[str, Any]:
    """Parse a ``--quality`` SPEC string into engine kwargs.

    Grammar: comma-separated ``key=value`` pairs —
    ``taps=chain+filter+decoder+lm`` (plus-separated subset), ``every=N``
    (observe every Nth frame per tap), ``psi=F`` (drift threshold),
    ``fast=SEC`` / ``slow=SEC`` (drift windows), ``nan_storm=N``,
    ``dead_frames=N``, ``sample_cap=N``, ``baseline=PATH`` (load a
    recorded drift baseline).  An empty spec means all defaults.
    Raises ValueError on unknown keys or out-of-range values.
    """
    out: Dict[str, Any] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                "bad --quality entry %r (want key=value)" % part)
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key == "taps":
            taps = tuple(v.strip() for v in val.split("+") if v.strip())
            bad = [t for t in taps if t not in TAP_KINDS]
            if not taps or bad:
                raise ValueError("bad taps %r (plus-separated subset of %s)"
                                 % (val, "+".join(TAP_KINDS)))
            out["taps"] = taps
        elif key in ("every", "nan_storm", "dead_frames", "sample_cap"):
            try:
                num = int(val)
            except ValueError:
                raise ValueError("bad value in --quality entry %r" % part)
            if num < 1:
                raise ValueError("%s must be >= 1 in --quality" % key)
            out[key] = num
        elif key in ("psi", "fast", "slow"):
            try:
                fnum = float(val)
            except ValueError:
                raise ValueError("bad value in --quality entry %r" % part)
            if fnum <= 0:
                raise ValueError("%s must be > 0 in --quality" % key)
            out[{"psi": "psi_threshold", "fast": "fast_window_s",
                 "slow": "slow_window_s"}[key]] = fnum
        elif key == "baseline":
            if not val:
                raise ValueError("baseline needs a path in --quality")
            out["baseline"] = val
        else:
            raise ValueError("unknown --quality key %r" % key)
    return out


def enable(spec: Optional[str] = None, **kwargs: Any) -> QualityEngine:
    """Install a fresh :class:`QualityEngine` into :data:`QUALITY_HOOK`.

    ``spec`` is a ``--quality`` SPEC string (see
    :func:`parse_quality_spec`); explicit kwargs override it.  A string
    ``baseline`` is loaded from disk here so the engine always holds a
    parsed :class:`~.drift.Baseline`.
    """
    global _ENGINE, QUALITY_HOOK
    merged: Dict[str, Any] = parse_quality_spec(spec) if spec else {}
    merged.update(kwargs)
    baseline = merged.pop("baseline", None)
    if isinstance(baseline, str):
        baseline = Baseline.load(baseline)
    eng = QualityEngine(baseline=baseline, **merged)
    _ENGINE = eng
    QUALITY_HOOK = eng
    _events.record("quality.capture_start",
                   "data-plane quality observation enabled")
    return eng


def disable() -> None:
    global _ENGINE, QUALITY_HOOK
    if _ENGINE is not None:
        _events.record("quality.capture_stop",
                       "data-plane quality observation disabled")
    _ENGINE = None
    QUALITY_HOOK = None


def snapshot() -> Dict[str, Any]:
    eng = _ENGINE
    if eng is None:
        return {"enabled": False, "taps": {}}
    return eng.snapshot()


def push_data() -> Optional[Dict[str, Any]]:
    """Compact snapshot for the fleet push doc; None while disabled."""
    eng = _ENGINE
    if eng is None:
        return None
    return eng.push_data()


def bundle_data() -> Dict[str, Any]:
    """Debug-bundle collector payload; raises while disabled so the
    bundle writer degrades this stanza to an error entry."""
    eng = _ENGINE
    if eng is None:
        raise RuntimeError("quality is not enabled")
    return eng.bundle_data()


def trace_points() -> List[Dict[str, Any]]:
    eng = _ENGINE
    if eng is None:
        return []
    return eng.trace_points()


def save_baseline(path: str) -> Optional[Baseline]:
    eng = _ENGINE
    if eng is None:
        return None
    return eng.save_baseline(path)


def report() -> str:
    eng = _ENGINE
    if eng is None:
        return "quality: off"
    return eng.report()


# Event helpers — this module owns the quality.* event-type literals so
# the nnslint event-layer-placement rule holds (health calls these
# lazily from its quality check, exactly like the slo burn events).

def event_anomaly_alert(component: str, data: Dict[str, Any]) -> None:
    _events.record(
        "quality.anomaly",
        "data-plane anomaly on %s" % component,
        severity="warning",
        component=component,
        tap=data.get("tap"),
        kind=data.get("anomaly"),
        detail=data.get("detail"),
    )
    # quality anomalies are a diag capture trigger — cold path, lazy
    # import keeps the obs package import graph acyclic
    from .. import diag as _diag
    dhook = _diag.DIAG_HOOK
    if dhook is not None:
        dhook.on_quality_anomaly(component, data)


def event_anomaly_recover(component: str, data: Dict[str, Any]) -> None:
    _events.record(
        "quality.recover",
        "data-plane anomaly cleared on %s" % component,
        component=component,
        tap=data.get("tap"),
    )


_env = os.environ.get("NNSTPU_QUALITY", "")
if _env == "1":
    enable()
elif _env:
    enable(_env)
del _env
