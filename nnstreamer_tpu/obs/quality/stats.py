"""Streaming tensor statistics — the numeric core of obs/quality.

Everything here is plain numpy over host-resident views; no jax, no
locks (the owning :class:`~nnstreamer_tpu.obs.quality.QualityEngine`
serializes access).  Three pieces:

* :class:`Welford` — numerically stable streaming mean/variance with a
  Chan-style bulk merge so a whole frame folds in as ONE state update
  (the per-element loop happens inside vectorized numpy, not Python).
* :class:`LogBucketSketch` — a tiny magnitude histogram keyed by the
  base-2 exponent of ``|x|`` plus dedicated ``zero`` / ``nonfinite``
  buckets.  Exponent buckets make the sketch scale-free (a float32
  activation tensor and an int8 quantized one land in comparable
  shapes) and keep it JSON-serializable for drift baselines.
* :class:`TapStats` — one tap's accumulator: Welford moments, min/max,
  NaN/Inf/zero counts, the cumulative sketch, and the inter-frame
  delta magnitude stream (mean ``|x_t - x_{t-1}|`` — the bandwidth
  signal a delta codec would exploit).

:func:`psi` computes the Population Stability Index between two
serialized sketches — the drift score obs/quality/drift.py windows.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["Welford", "LogBucketSketch", "TapStats", "psi",
           "PSI_EPSILON", "EXP_MIN", "EXP_MAX"]

#: exponent buckets clamp here — 2^±64 covers every sane activation
EXP_MIN, EXP_MAX = -64, 64
#: probability floor so empty buckets don't blow PSI up to infinity
PSI_EPSILON = 1e-6


class Welford:
    """Streaming mean/variance (population), stable under cancellation.

    ``add_array`` merges a whole chunk via Chan's parallel update: the
    chunk's own moments come from vectorized numpy, then fold into the
    running state in O(1) — exactness against ``np.mean``/``np.var`` on
    the concatenated data is pinned by tests/test_quality.py.
    """

    __slots__ = ("n", "mean", "m2")

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def add(self, x: float) -> None:
        self.n += 1
        delta = x - self.mean
        self.mean += delta / self.n
        self.m2 += delta * (x - self.mean)

    def add_array(self, arr: np.ndarray,
                  mean: Optional[float] = None) -> None:
        nb = int(arr.size)
        if nb == 0:
            return
        mb = float(arr.mean()) if mean is None else mean
        d = (arr - mb).ravel()
        m2b = float(np.dot(d, d))
        tot = self.n + nb
        delta = mb - self.mean
        self.m2 += m2b + delta * delta * (self.n * nb / tot)
        self.mean += delta * (nb / tot)
        self.n = tot

    @property
    def variance(self) -> float:
        return self.m2 / self.n if self.n else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))

    def as_dict(self) -> Dict[str, float]:
        return {"n": self.n, "mean": self.mean, "var": self.variance}


class LogBucketSketch:
    """Magnitude histogram over exponent buckets.

    Finite non-zero values land in bucket ``floor(log2(|x|))`` clamped
    to ``[EXP_MIN, EXP_MAX]``; zeros and non-finite values get their
    own buckets.  Serializes to ``{"e<k>": n, "zero": n,
    "nonfinite": n}`` — the JSON shape drift baselines freeze.
    """

    __slots__ = ("counts", "zeros", "nonfinite")

    def __init__(self) -> None:
        self.counts: Dict[int, int] = {}
        self.zeros = 0
        self.nonfinite = 0

    @classmethod
    def of(cls, x: np.ndarray) -> "LogBucketSketch":
        """Sketch one (flat) array of any numeric dtype."""
        x = np.asarray(x)
        if x.dtype.kind != "f":
            x = x.astype(np.float64)
        nonfinite = int(x.size) - int(np.count_nonzero(np.isfinite(x)))
        fin = x[np.isfinite(x)] if nonfinite else x
        return cls._of_finite(fin, nonfinite)

    @classmethod
    def _of_finite(cls, fin: np.ndarray, nonfinite: int,
                   zeros: Optional[int] = None) -> "LogBucketSketch":
        """Sketch a finite-only array plus the dropped nonfinite count
        (the hot path — ``TapStats.observe`` already holds both).

        The bucket exponent comes from ``np.frexp``: ``|x|`` in
        ``[2^(e-1), 2^e)`` means ``floor(log2(|x|)) == e - 1`` by
        integer arithmetic, exact even where a transcendental ``log2``
        rounds across a power of two.  Tallying is one ``np.bincount``
        over the clipped bucket offsets instead of ``np.unique``'s
        sort — the difference is ~4x on sketch cost per frame."""
        sk = cls()
        sk.nonfinite = int(nonfinite)
        n_nz = int(np.count_nonzero(fin)) if zeros is None \
            else int(fin.size) - int(zeros)
        sk.zeros = int(fin.size) - n_nz
        if n_nz:
            nz = fin[fin != 0.0] if sk.zeros else fin
            e = np.frexp(nz)[1]
            e -= 1 + EXP_MIN
            np.clip(e, 0, EXP_MAX - EXP_MIN, out=e)
            bc = np.bincount(e, minlength=EXP_MAX - EXP_MIN + 1)
            for i in np.nonzero(bc)[0]:
                sk.counts[int(i) + EXP_MIN] = int(bc[i])
        return sk

    def merge(self, other: "LogBucketSketch") -> None:
        self.zeros += other.zeros
        self.nonfinite += other.nonfinite
        for (k, c) in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + c

    @property
    def total(self) -> int:
        return self.zeros + self.nonfinite + sum(self.counts.values())

    def as_dict(self) -> Dict[str, int]:
        out = {f"e{k}": c for (k, c) in sorted(self.counts.items())}
        out["zero"] = self.zeros
        out["nonfinite"] = self.nonfinite
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "LogBucketSketch":
        sk = cls()
        for (k, c) in d.items():
            if k == "zero":
                sk.zeros = int(c)
            elif k == "nonfinite":
                sk.nonfinite = int(c)
            elif k.startswith("e"):
                sk.counts[int(k[1:])] = int(c)
        return sk


def psi(ref: Dict[str, int], live: Dict[str, int],
        eps: float = PSI_EPSILON) -> float:
    """Population Stability Index between two serialized sketches.

    ``sum((p - q) * ln(p / q))`` over the union of bucket keys, with
    probabilities floored at ``eps`` so a bucket present on one side
    only contributes a large-but-finite term.  0 means identical;
    >= 0.2 is the conventional "significant shift" line the default
    drift threshold uses.
    """
    ref_total = max(sum(ref.values()), 1)
    live_total = max(sum(live.values()), 1)
    score = 0.0
    for key in set(ref) | set(live):
        p = max(live.get(key, 0) / live_total, eps)
        q = max(ref.get(key, 0) / ref_total, eps)
        score += (p - q) * math.log(p / q)
    return score


class TapStats:
    """Cumulative statistics for one tap, fed one frame at a time.

    ``observe`` returns a per-frame info dict the engine's anomaly
    rules consume: ``nan_frame`` (any NaN/Inf present), ``dead`` (all
    finite values identical — covers all-zero AND stuck-constant
    outputs), the frame mean, the frame's own sketch (the drift PSI
    sample), and the inter-frame delta magnitude when the previous
    frame had the same shape.

    Frames larger than ``sample_cap`` elements are stride-sampled so a
    4K video tensor costs the same as a thumbnail — the moments become
    estimates but the anomaly signals (NaN anywhere in the sample,
    constant output) stay representative.
    """

    __slots__ = ("sample_cap", "frames", "elements", "nan_count",
                 "inf_count", "zero_count", "min", "max", "welford",
                 "delta", "sketch", "_last", "_last_all_finite")

    def __init__(self, sample_cap: int = 2048) -> None:
        self.sample_cap = int(sample_cap)
        self.frames = 0
        self.elements = 0
        self.nan_count = 0
        self.inf_count = 0
        self.zero_count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.welford = Welford()
        self.delta = Welford()     # stream of mean |x_t - x_{t-1}|
        self.sketch = LogBucketSketch()
        self._last: Optional[np.ndarray] = None
        self._last_all_finite = False

    def observe(self, arr: np.ndarray) -> Dict[str, Any]:
        x = np.asarray(arr).reshape(-1)
        if x.size > self.sample_cap:
            x = x[::-(-x.size // self.sample_cap)]
        x = x.astype(np.float64, copy=False)
        n = int(x.size)
        n_fin = int(np.count_nonzero(np.isfinite(x)))
        all_finite = n_fin == n
        if all_finite:
            nan_ct = inf_ct = 0
            fin = x
        else:
            nan_ct = int(np.count_nonzero(np.isnan(x)))
            inf_ct = n - n_fin - nan_ct
            fin = x[np.isfinite(x)]
        zero_ct = int(fin.size) - int(np.count_nonzero(fin))

        self.frames += 1
        self.elements += n
        self.nan_count += nan_ct
        self.inf_count += inf_ct
        self.zero_count += zero_ct
        frame_mean = float("nan")
        dead = False
        if fin.size:
            frame_mean = float(fin.mean())
            self.welford.add_array(fin, mean=frame_mean)
            fmin, fmax = float(fin.min()), float(fin.max())
            self.min = fmin if self.min is None else min(self.min, fmin)
            self.max = fmax if self.max is None else max(self.max, fmax)
            dead = all_finite and fmin == fmax

        frame_sketch = LogBucketSketch._of_finite(fin, n - n_fin,
                                                  zeros=zero_ct)
        self.sketch.merge(frame_sketch)

        delta_mag: Optional[float] = None
        last = self._last
        if last is not None and last.shape == x.shape:
            d = x - last
            np.abs(d, out=d)
            if not (all_finite and self._last_all_finite):
                d = d[np.isfinite(d)]
            if d.size:
                delta_mag = float(d.mean())
                self.delta.add(delta_mag)
        self._last = x
        self._last_all_finite = all_finite

        return {"nan_frame": (nan_ct + inf_ct) > 0, "dead": dead,
                "mean": frame_mean, "sketch": frame_sketch,
                "delta": delta_mag}

    def snapshot(self) -> Dict[str, Any]:
        return {
            "frames": self.frames,
            "elements": self.elements,
            "nan": self.nan_count,
            "inf": self.inf_count,
            "zero": self.zero_count,
            "min": self.min,
            "max": self.max,
            "moments": self.welford.as_dict(),
            "delta": self.delta.as_dict(),
            "sketch": self.sketch.as_dict(),
        }
