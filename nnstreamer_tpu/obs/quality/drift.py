"""Drift detection — frozen baselines plus multi-window PSI burn.

A :class:`Baseline` freezes each tap's magnitude sketch (the
``--quality-record`` reference window) to JSON; live traffic scores
every observed frame's sketch against it with
:func:`~nnstreamer_tpu.obs.quality.stats.psi` and feeds the score into
a :class:`DriftWindows` — the same multi-window burn shape obs/slo.py
uses for error budgets: a fast and a slow horizon over a bounded ring
of timestamped scores, an injectable clock, and a breach that requires
the mean PSI to clear the threshold on BOTH windows.  The fast window
makes detection quick; the slow window keeps a single weird frame from
paging anyone.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

__all__ = ["Baseline", "DriftWindows", "BASELINE_VERSION",
           "DEFAULT_FAST_WINDOW_S", "DEFAULT_SLOW_WINDOW_S",
           "DEFAULT_PSI_THRESHOLD"]

BASELINE_VERSION = 1

#: drift windows are much shorter than SLO burn windows — distribution
#: shift is per-frame signal, not per-request accounting
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
#: PSI >= 0.2 is the conventional "significant population shift" line
DEFAULT_PSI_THRESHOLD = 0.2
_WINDOW_SCORES = 4096


class Baseline:
    """Per-tap reference sketches, serializable to a JSON file."""

    def __init__(self, taps: Dict[str, Dict[str, int]],
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.taps = dict(taps)
        self.meta = dict(meta or {})

    def sketch_for(self, tap: str) -> Optional[Dict[str, int]]:
        return self.taps.get(tap)

    def save(self, path: str) -> None:
        doc = {"version": BASELINE_VERSION, "taps": self.taps,
               "meta": self.meta}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        version = doc.get("version")
        if version != BASELINE_VERSION:
            raise ValueError(
                f"unsupported quality baseline version {version!r} "
                f"(want {BASELINE_VERSION})")
        taps = doc.get("taps")
        if not isinstance(taps, dict):
            raise ValueError("quality baseline has no taps table")
        return cls({str(t): {str(k): int(c) for (k, c) in sk.items()}
                    for (t, sk) in taps.items()}, meta=doc.get("meta"))


class DriftWindows:
    """Fast/slow mean-PSI evaluation over a bounded score ring.

    One instance per tap.  ``add`` timestamps a score with the
    injectable clock; ``evaluate`` averages scores inside each horizon
    and breaches only when BOTH horizons hold data and both means are
    at or above the threshold — the obs/slo multi-window contract.
    """

    __slots__ = ("fast_window_s", "slow_window_s", "psi_threshold",
                 "clock", "scores")

    def __init__(self, *, fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 psi_threshold: float = DEFAULT_PSI_THRESHOLD,
                 window_scores: int = _WINDOW_SCORES,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not (0 < fast_window_s <= slow_window_s):
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if psi_threshold <= 0:
            raise ValueError("psi_threshold must be > 0")
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.psi_threshold = float(psi_threshold)
        self.clock = clock
        self.scores: deque = deque(maxlen=window_scores)

    def add(self, score: float, now: Optional[float] = None) -> None:
        t = self.clock() if now is None else now
        self.scores.append((t, float(score)))

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        t = self.clock() if now is None else now
        windows: Dict[str, Dict[str, Any]] = {}
        breached = True
        for (wname, wlen) in (("fast", self.fast_window_s),
                              ("slow", self.slow_window_s)):
            recent = [s for (ts, s) in self.scores if t - ts <= wlen]
            n = len(recent)
            mean = (sum(recent) / n) if n else 0.0
            windows[wname] = {"n": n, "mean_psi": mean}
            if not n or mean < self.psi_threshold:
                breached = False
        return {"windows": windows, "breached": breached,
                "psi_threshold": self.psi_threshold}
