"""Process-wide metrics registry: counters, gauges, histograms.

The reference exposes per-filter invoke stats only as GObject runtime
props (tensor_filter.c:366-400) and leans on out-of-tree GstShark
tracers for anything per-element; there is no always-on telemetry a
serving fleet could scrape. This module is the in-tree answer: one
thread-safe ``MetricsRegistry`` every layer (graph, query, serving)
feeds, with snapshot-to-dict for programmatic consumers (the
``PipelineTracer`` report is one) and Prometheus text exposition for
the ``/metrics`` endpoint (obs/exporter.py). Stdlib only.

Design points:
  * **Families and children.** ``registry.counter(name, help, labels)``
    registers (or returns the existing) family; ``family.labels(*vals)``
    returns the mutable child series. Label-less families proxy
    ``inc``/``set``/``observe`` straight through to their single child.
  * **Cheap no-op when disabled.** Every mutation checks one registry
    flag and returns; nothing allocates. The pipeline hot path is even
    cheaper: element chains are only wrapped at all when metrics are
    enabled at ``Pipeline.start`` time (obs/instrument.py), so the
    disabled cost there is exactly zero.
  * **Fixed log-spaced latency buckets.** Histograms default to a
    1-2.5-5 decade ladder from 10 us to 50 s — per-phase latency
    *distributions*, not averages, are the signal worth capturing
    (arXiv:2008.01040's learned performance models feed on exactly
    these); the max is tracked besides the buckets so tail reporting
    (tracer ``max_us``) needs no +Inf quantile math.

Naming convention (enforced by scripts/check_metric_names.py, wired
into tier 1): ``nnstpu_<layer>_<name>_<unit>`` with layer in
{pipeline, query, serving}; counters end in ``_total``, histograms in
``_seconds``, gauges in ``_depth``/``_slots``/``_bytes``.
"""

from __future__ import annotations

import os
import threading
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS", "registry", "enabled", "enable", "disable",
]

#: 1-2.5-5 per decade, 10 us .. 50 s (21 buckets + implicit +Inf)
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** e * m, 12) for e in range(-5, 2) for m in (1.0, 2.5, 5.0))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-line escaping (text format 0.0.4): backslash and newline
    only — quotes are legal in help text."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.10g}"


class _Child:
    """One labeled series. Mutations are guarded by the owning family's
    lock and no-op when the registry is disabled."""

    def __init__(self, family: "_Family", labelvalues: Tuple[str, ...]):
        self._family = family
        self._labels = labelvalues


class Counter(_Child):
    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._family._registry._enabled:
            return
        if n < 0:
            raise ValueError("counters only go up")
        with self._family._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Child):
    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        if not self._family._registry._enabled:
            return
        with self._family._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not self._family._registry._enabled:
            return
        with self._family._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate ``fn`` at collection time instead of storing writes —
        zero hot-path cost for depth-style gauges (queue occupancy,
        in-flight windows) whose state already lives elsewhere."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a dead callback reads 0
                return 0.0
        return self._value


class Histogram(_Child):
    def __init__(self, family, labelvalues):
        super().__init__(family, labelvalues)
        self._bucket_counts = [0] * len(family._buckets)
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, v: float) -> None:
        if not self._family._registry._enabled:
            return
        v = float(v)
        i = bisect_left(self._family._buckets, v)
        with self._family._lock:
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max


_CHILD_CLASSES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named metric with a fixed label schema; children per label
    combination are created on demand and cached forever (bounded by
    label cardinality, which the call sites keep small)."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 mtype: str, labelnames: Tuple[str, ...],
                 buckets: Tuple[float, ...] = ()):
        self._registry = registry
        self.name = name
        self.help = help
        self.type = mtype
        self.labelnames = labelnames
        self._buckets = buckets
        self._children: Dict[Tuple[str, ...], _Child] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def labels(self, *values: Any, **kv: Any) -> Any:
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            values = tuple(kv[n] for n in self.labelnames)
        vals = tuple(str(v) for v in values)
        if len(vals) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {vals}")
        child = self._children.get(vals)
        if child is None:
            with self._lock:
                child = self._children.get(vals)
                if child is None:
                    child = _CHILD_CLASSES[self.type](self, vals)
                    self._children[vals] = child
        return child

    # -- label-less convenience: the family IS its single child -------- #
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def samples(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Thread-safe registry of metric families.

    Re-registering a name is idempotent when type/labels/buckets agree
    (every call site just declares what it needs) and raises otherwise —
    silent schema drift is how dashboards rot.
    """

    def __init__(self, enabled: bool = True):
        self._families: Dict[str, _Family] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._enabled = bool(enabled)

    # -- enable/disable ------------------------------------------------ #
    @property
    def is_enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    # -- registration -------------------------------------------------- #
    def _register(self, name: str, help: str, mtype: str,
                  labelnames: Sequence[str],
                  buckets: Tuple[float, ...] = ()) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype or fam.labelnames != labelnames or \
                        (mtype == "histogram" and fam._buckets != buckets):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.type}{fam.labelnames}, conflicting "
                        f"re-registration as {mtype}{labelnames}")
                return fam
            fam = _Family(self, name, help, mtype, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._register(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._register(name, help, "gauge", labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> _Family:
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        return self._register(name, help, "histogram", labelnames, b)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def reset(self) -> None:
        """Drop every family (tests over private registries). Cached
        family/child handles held by call sites keep working but detach
        from future snapshots — never reset the process-global registry
        mid-flight."""
        with self._lock:
            self._families.clear()

    # -- collection ---------------------------------------------------- #
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """{name: {type, help, series: [{labels, ...values}]}} — the
        programmatic view (tracer reports, tests, JSON dumps)."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            series = []
            for vals, child in fam.samples():
                labels = dict(zip(fam.labelnames, vals))
                if fam.type == "histogram":
                    with fam._lock:
                        counts = list(child._bucket_counts)
                        s, c, mx = child._sum, child._count, child._max
                    cum = 0
                    buckets = {}
                    for bound, n in zip(fam._buckets, counts):
                        cum += n
                        buckets[bound] = cum
                    series.append({"labels": labels, "count": c, "sum": s,
                                   "max": mx, "buckets": buckets})
                else:
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.type, "help": fam.help,
                             "series": series}
        return out

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for fam in families:
            samples = fam.samples()
            if not samples:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.type}")
            for vals, child in samples:
                base = ",".join(
                    f'{n}="{_escape_label(v)}"'
                    for n, v in zip(fam.labelnames, vals))
                if fam.type == "histogram":
                    with fam._lock:
                        counts = list(child._bucket_counts)
                        s, c = child._sum, child._count
                    cum = 0
                    for bound, n in zip(fam._buckets, counts):
                        cum += n
                        le = f'le="{_fmt(bound)}"'
                        lbl = f"{base},{le}" if base else le
                        lines.append(f"{fam.name}_bucket{{{lbl}}} {cum}")
                    le = 'le="+Inf"'
                    lbl = f"{base},{le}" if base else le
                    lines.append(f"{fam.name}_bucket{{{lbl}}} {c}")
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{fam.name}_sum{suffix} {_fmt(s)}")
                    lines.append(f"{fam.name}_count{suffix} {c}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{fam.name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + "\n" if lines else ""


# --------------------------------------------------------------------------- #
# Process-global registry
# --------------------------------------------------------------------------- #

#: disabled by default: instrumentation costs nothing until something
#: (the exporter, the CLI flag, NNSTPU_METRICS=1, or an explicit
#: enable()) turns collection on
_REGISTRY = MetricsRegistry(
    enabled=os.environ.get("NNSTPU_METRICS", "") == "1")


def registry() -> MetricsRegistry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY._enabled


def enable() -> None:
    """Turn collection on. Call BEFORE building pipelines/engines: the
    element-chain fast path decides at Pipeline.start whether to wrap
    at all."""
    _REGISTRY.enable()


def disable() -> None:
    _REGISTRY.disable()
