"""Per-tenant cost attribution, goodput accounting, and SLO burn-rate tracking.

The sched layer (PR 11) made the chip multi-tenant; this module makes the
*bill* multi-tenant.  It answers three questions the system-level metrics
cannot:

1. **Cost attribution** — which tenant burned the device-seconds?  Each
   coalesced batch's busy time is split across member tenants proportional
   to row count; each item's queue wait is charged to its own tenant; LM
   engine phase intervals (prefill/decode/verify) and router dispatch bytes
   are attributed per session.  Per-tenant ``device_seconds`` /
   ``wait_seconds`` sum to the engine totals — conservation is testable.

2. **Goodput** — deadline-met work per device-second.  Every completed unit
   of work lands in ``nnstpu_slo_goodput_total{tenant,outcome}`` with
   outcome ``met`` / ``missed`` / ``shed`` plus a latency histogram split
   by outcome.

3. **SLO objectives + burn rate** — declare per-tenant objectives
   (``p99_ms``, ``goodput_ratio``) via ``nns-launch --slo
   TENANT:p99=50:goodput=0.99`` or :func:`set_objective`.  Burn rates are
   evaluated over a fast (5m) and slow (1h) window from a bounded
   ring-buffered event log with an injectable clock; a breach requires
   burn >= threshold on *both* windows (multi-window alerting), surfaces as
   a DEGRADED ``slo:<tenant>`` component in the health registry, emits
   ``slo.burn_alert``, shows in ``/debug/slo`` and the fleet rollup, and
   draws a per-tenant goodput counter lane in the Perfetto export.

Zero-overhead-when-off: the three hooks below are module globals that stay
``None`` until :func:`enable` is called.  Instrumented call sites pay one
module-attribute load plus a ``None`` check — the same contract as
``obs.profile`` and ``obs.chaos``.  Set ``NNSTPU_SLO=1`` to enable at
import.

Tenant-label cardinality is bounded: at most ``max_tenants`` accounts are
kept (overflow folds into ``_overflow``), and router sessions only map to
a tenant label when that tenant is already registered (unknown sessions
fold into ``_other``).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import events as _events
from . import health as _health
from . import metrics as _metrics

__all__ = [
    "SloRegistry",
    "enable",
    "disable",
    "enabled",
    "slo_registry",
    "set_objective",
    "snapshot",
    "push_data",
    "trace_points",
    "report",
    "parse_slo_spec",
    "event_burn_alert",
    "event_burn_recover",
]

# Defaults -----------------------------------------------------------------

DEFAULT_FAST_WINDOW_S = 300.0     # 5 minutes
DEFAULT_SLOW_WINDOW_S = 3600.0    # 1 hour
DEFAULT_BURN_THRESHOLD = 1.0
DEFAULT_MAX_TENANTS = 64
DEFAULT_WINDOW_EVENTS = 4096
P99_BUDGET = 0.01                 # a p99 objective budgets 1% of events
OTHER_TENANT = "_other"           # unregistered router sessions fold here
OVERFLOW_TENANT = "_overflow"     # accounts past max_tenants fold here
_OUTCOMES = ("met", "missed", "shed")
_TRACE_CAP = 4096

# Hooks --------------------------------------------------------------------
# None unless enable() was called; consumers load the module attribute and
# None-check before every use so a disabled run pays nothing.

#: Consumed by sched.engine.DeviceEngine at batch commit and shed.
SCHED_SLO_HOOK: Optional["SloRegistry"] = None
#: Consumed by serving LMEngine/TPLMEngine phase + retire + shed sites.
ENGINE_SLO_HOOK: Optional["SloRegistry"] = None
#: Consumed by query.router.QueryRouter per dispatch.
ROUTER_SLO_HOOK: Optional["SloRegistry"] = None


class _TenantAccount:
    """Mutable per-tenant accumulator. Guarded by the registry lock."""

    __slots__ = ("name", "device_s", "wait_s", "bytes_tx", "bytes_rx",
                 "outcomes", "shed_total", "events")

    def __init__(self, name: str, window_events: int) -> None:
        self.name = name
        self.device_s = 0.0
        self.wait_s = 0.0
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.outcomes = {o: 0 for o in _OUTCOMES}
        self.shed_total = 0
        # (t, outcome, latency_s) ring feeding the burn-rate windows.
        self.events: deque = deque(maxlen=window_events)


class SloRegistry:
    """Per-tenant accounting plus multi-window SLO burn-rate evaluation.

    One instance is installed into the three module hooks by :func:`enable`.
    All recording methods are thread-safe and cheap; metric emission happens
    outside the lock.
    """

    def __init__(self, *, fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 max_tenants: int = DEFAULT_MAX_TENANTS,
                 window_events: int = DEFAULT_WINDOW_EVENTS,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not (0 < fast_window_s <= slow_window_s):
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if burn_threshold <= 0:
            raise ValueError("burn_threshold must be > 0")
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.max_tenants = int(max_tenants)
        self.window_events = int(window_events)
        self.clock = clock
        self._lock = threading.Lock()
        # Guarded by _lock:
        self._accounts: Dict[str, _TenantAccount] = {}
        self._objectives: Dict[str, Dict[str, float]] = {}
        self._trace: deque = deque(maxlen=_TRACE_CAP)
        self._register_metrics()

    # -- metrics ----------------------------------------------------------

    def _register_metrics(self) -> None:
        reg = _metrics.registry()
        self._m_goodput = reg.counter(
            "nnstpu_slo_goodput_total",
            "Completed work units per tenant split by deadline outcome",
            labelnames=("tenant", "outcome"))
        self._m_latency = reg.histogram(
            "nnstpu_slo_latency_seconds",
            "Per-tenant end-to-end latency split by deadline outcome",
            labelnames=("tenant", "outcome"))
        self._m_device = reg.histogram(
            "nnstpu_slo_device_seconds",
            "Per-tenant attributed device busy time per batch share",
            labelnames=("tenant",))
        self._m_wait = reg.histogram(
            "nnstpu_slo_wait_seconds",
            "Per-tenant queue wait per work item",
            labelnames=("tenant",))
        self._m_shed = reg.counter(
            "nnstpu_slo_shed_total",
            "Work units shed per tenant by site",
            labelnames=("tenant", "site"))
        self._m_bytes = reg.counter(
            "nnstpu_slo_bytes_total",
            "Bytes moved per tenant over the query wire by direction",
            labelnames=("tenant", "direction"))
        self._m_burn = reg.gauge(
            "nnstpu_slo_burn_ratio",
            "SLO error-budget burn rate per tenant/objective/window",
            labelnames=("tenant", "objective", "window"))

    # -- accounts (lock held) ---------------------------------------------

    def _account(self, name: str) -> _TenantAccount:
        acct = self._accounts.get(name)
        if acct is None:
            if len(self._accounts) >= self.max_tenants:
                name = OVERFLOW_TENANT
                acct = self._accounts.get(name)
                if acct is None:
                    acct = _TenantAccount(name, self.window_events)
                    self._accounts[name] = acct
            else:
                acct = _TenantAccount(name, self.window_events)
                self._accounts[name] = acct
        return acct

    def _record_outcome(self, acct: _TenantAccount, outcome: str,
                        latency_s: float, t: float) -> None:
        acct.outcomes[outcome] += 1
        if outcome == "shed":
            acct.shed_total += 1
        acct.events.append((t, outcome, latency_s))
        self._trace.append({
            "t_ns": time.monotonic_ns(),
            "tenant": acct.name,
            "met": acct.outcomes["met"],
            "missed": acct.outcomes["missed"],
            "shed": acct.outcomes["shed"],
        })

    # -- recording hooks --------------------------------------------------

    def record_sched_batch(self, engine: str, busy_s: float,
                           members: Sequence[Tuple[str, float, int, Any]],
                           ) -> None:
        """Attribute one committed batch to its member tenants.

        ``members`` is ``[(tenant, wait_s, rows, deadline), ...]``.  Busy
        time splits proportional to rows so the per-tenant sum equals
        ``busy_s`` exactly; waits charge each tenant directly.
        """
        if not members:
            return
        total_rows = sum(max(int(r), 1) for (_, _, r, _) in members)
        t = self.clock()
        emit: List[Tuple[str, str, float, float, float]] = []
        with self._lock:
            for (tenant, wait_s, rows, deadline) in members:
                share = busy_s * (max(int(rows), 1) / total_rows)
                acct = self._account(tenant)
                acct.device_s += share
                acct.wait_s += wait_s
                outcome = "met"
                if deadline is not None:
                    try:
                        if deadline.expired():
                            outcome = "missed"
                    except Exception:
                        pass
                latency = wait_s + share
                self._record_outcome(acct, outcome, latency, t)
                emit.append((acct.name, outcome, share, wait_s, latency))
        for (name, outcome, share, wait_s, latency) in emit:
            self._m_device.labels(name).observe(share)
            self._m_wait.labels(name).observe(wait_s)
            self._m_goodput.labels(name, outcome).inc()
            self._m_latency.labels(name, outcome).observe(latency)

    def record_shed(self, tenant: str, site: str,
                    wait_s: float = 0.0) -> None:
        """One work unit dropped before execution (deadline or pressure).

        The shed's wait feeds the goodput/latency window but NOT the
        tenant's ``wait_s`` account — shed work never reached the device,
        so attribution conservation stays exact against engine totals.
        """
        t = self.clock()
        with self._lock:
            acct = self._account(tenant)
            self._record_outcome(acct, "shed", wait_s, t)
            name = acct.name
        self._m_shed.labels(name, site).inc()
        self._m_goodput.labels(name, "shed").inc()
        self._m_latency.labels(name, "shed").observe(wait_s)

    def record_outcome(self, tenant: str, outcome: str,
                       latency_s: float) -> None:
        """A completed request (serving retire path): met or missed."""
        if outcome not in _OUTCOMES:
            outcome = "met"
        t = self.clock()
        with self._lock:
            acct = self._account(tenant)
            self._record_outcome(acct, outcome, latency_s, t)
            name = acct.name
        self._m_goodput.labels(name, outcome).inc()
        self._m_latency.labels(name, outcome).observe(latency_s)

    def record_engine_phase(self, tenant: str, phase: str,
                            dur_s: float) -> None:
        """Attribute one LM engine phase interval (prefill/decode/verify)."""
        with self._lock:
            acct = self._account(tenant)
            acct.device_s += dur_s
            name = acct.name
        self._m_device.labels(name).observe(dur_s)

    def record_dispatch(self, session: Optional[str], bytes_tx: int,
                        bytes_rx: int) -> None:
        """Attribute one router dispatch's wire bytes to a session tenant.

        Sessions only map to a tenant label when that name is already a
        registered account or objective — everything else folds into
        ``_other`` so the label set stays bounded.
        """
        with self._lock:
            if session is not None and (session in self._accounts
                                        or session in self._objectives):
                acct = self._account(session)
            else:
                acct = self._account(OTHER_TENANT)
            acct.bytes_tx += int(bytes_tx)
            acct.bytes_rx += int(bytes_rx)
            name = acct.name
        self._m_bytes.labels(name, "tx").inc(int(bytes_tx))
        self._m_bytes.labels(name, "rx").inc(int(bytes_rx))

    # -- objectives + burn ------------------------------------------------

    def set_objective(self, tenant: str, *, p99_ms: Optional[float] = None,
                      goodput_ratio: Optional[float] = None) -> None:
        if p99_ms is None and goodput_ratio is None:
            raise ValueError("objective needs p99_ms and/or goodput_ratio")
        if p99_ms is not None and p99_ms <= 0:
            raise ValueError("p99_ms must be > 0")
        if goodput_ratio is not None and not (0.0 < goodput_ratio < 1.0):
            raise ValueError("goodput_ratio must be in (0, 1)")
        obj: Dict[str, float] = {}
        if p99_ms is not None:
            obj["p99_ms"] = float(p99_ms)
        if goodput_ratio is not None:
            obj["goodput_ratio"] = float(goodput_ratio)
        with self._lock:
            self._objectives[tenant] = obj
            self._account(tenant)
        self._ensure_component(tenant)

    def _ensure_component(self, tenant: str) -> None:
        ref = weakref.ref(self)

        def probe() -> Optional[Dict[str, Any]]:
            reg = ref()
            if reg is None or _SLO is not reg:
                return None  # retire the component
            with reg._lock:
                if tenant not in reg._objectives:
                    return None
            return reg.evaluate(tenant)

        _health.component(f"slo:{tenant}", kind="slo", probe=probe,
                          attrs={"tenant": tenant})

    def evaluate(self, tenant: str,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """Compute burn rates for one tenant over both windows.

        Burn semantics: for a ``goodput_ratio`` objective the burn is the
        observed bad fraction (missed+shed)/n divided by the budgeted bad
        fraction (1 - ratio).  For a ``p99_ms`` objective the burn is the
        fraction of events that were shed or slower than the target,
        divided by the 1% budget a p99 implies.  Burn 1.0 means the budget
        is being consumed exactly at the sustainable rate; a breach
        requires burn >= threshold on BOTH windows.
        """
        t = self.clock() if now is None else now
        with self._lock:
            obj = dict(self._objectives.get(tenant, {}))
            acct = self._accounts.get(tenant)
            evs = list(acct.events) if acct is not None else []
        windows: Dict[str, Dict[str, Any]] = {}
        for (wname, wlen) in (("fast", self.fast_window_s),
                              ("slow", self.slow_window_s)):
            recent = [(ts, o, lat) for (ts, o, lat) in evs
                      if t - ts <= wlen]
            n = len(recent)
            met = sum(1 for (_, o, _) in recent if o == "met")
            missed = sum(1 for (_, o, _) in recent if o == "missed")
            shed = sum(1 for (_, o, _) in recent if o == "shed")
            burn: Dict[str, float] = {}
            if n:
                if "goodput_ratio" in obj:
                    budget = 1.0 - obj["goodput_ratio"]
                    burn["goodput"] = ((missed + shed) / n) / budget
                if "p99_ms" in obj:
                    p99_s = obj["p99_ms"] / 1e3
                    slow = sum(1 for (_, o, lat) in recent
                               if o == "shed" or lat > p99_s)
                    burn["p99"] = (slow / n) / P99_BUDGET
            else:
                if "goodput_ratio" in obj:
                    burn["goodput"] = 0.0
                if "p99_ms" in obj:
                    burn["p99"] = 0.0
            windows[wname] = {
                "n": n, "met": met, "missed": missed, "shed": shed,
                "goodput": (met / n) if n else 1.0,
                "burn": burn,
            }
        breached_objs: List[str] = []
        worst_obj: Optional[str] = None
        worst_burn = -1.0
        for oname in windows["fast"]["burn"]:
            fast_b = windows["fast"]["burn"][oname]
            slow_b = windows["slow"]["burn"][oname]
            if (fast_b >= self.burn_threshold
                    and slow_b >= self.burn_threshold):
                breached_objs.append(oname)
            eff = min(fast_b, slow_b)
            if eff > worst_burn:
                worst_burn = eff
                worst_obj = oname
            self._m_burn.labels(tenant, oname, "fast").set(fast_b)
            self._m_burn.labels(tenant, oname, "slow").set(slow_b)
        return {
            "tenant": tenant,
            "objective": obj,
            "windows": windows,
            "breached": bool(breached_objs),
            "breached_objectives": breached_objs,
            "worst_objective": worst_obj,
            "worst_burn": max(worst_burn, 0.0),
            "burn_threshold": self.burn_threshold,
        }

    # -- export -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            names = list(self._accounts)
            rows: Dict[str, Dict[str, Any]] = {}
            for name in names:
                acct = self._accounts[name]
                rows[name] = {
                    "device_seconds": acct.device_s,
                    "wait_seconds": acct.wait_s,
                    "bytes_tx": acct.bytes_tx,
                    "bytes_rx": acct.bytes_rx,
                    "outcomes": dict(acct.outcomes),
                    "shed_total": acct.shed_total,
                    "objective": dict(self._objectives.get(name, {})),
                }
            objective_names = list(self._objectives)
        for name in objective_names:
            # Health may have been enabled after the objective was set —
            # re-registering is a cheap get-or-create.
            self._ensure_component(name)
            row = rows.setdefault(name, {
                "device_seconds": 0.0, "wait_seconds": 0.0,
                "bytes_tx": 0, "bytes_rx": 0,
                "outcomes": {o: 0 for o in _OUTCOMES}, "shed_total": 0,
                "objective": {},
            })
            row["burn"] = self.evaluate(name)
        return {
            "enabled": True,
            "burn_threshold": self.burn_threshold,
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "tenants": rows,
        }

    def trace_points(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._trace)

    def report(self) -> str:
        snap = self.snapshot()
        lines = ["slo: per-tenant accounting"]
        for (name, row) in sorted(snap["tenants"].items()):
            out = row["outcomes"]
            lines.append(
                "  %-16s device=%.4fs wait=%.4fs met=%d missed=%d shed=%d"
                % (name, row["device_seconds"], row["wait_seconds"],
                   out["met"], out["missed"], out["shed"]))
            burn = row.get("burn")
            if burn and burn["objective"]:
                state = "BREACHED" if burn["breached"] else "ok"
                lines.append(
                    "  %-16s slo=%s worst_burn=%.2f (%s) %s"
                    % ("", burn["objective"], burn["worst_burn"],
                       burn["worst_objective"], state))
        return "\n".join(lines)


# Module API ---------------------------------------------------------------

_SLO: Optional[SloRegistry] = None


def slo_registry() -> Optional[SloRegistry]:
    return _SLO


def enabled() -> bool:
    return _SLO is not None


def enable(**kwargs: Any) -> SloRegistry:
    """Install a fresh :class:`SloRegistry` into the three hooks."""
    global _SLO, SCHED_SLO_HOOK, ENGINE_SLO_HOOK, ROUTER_SLO_HOOK
    reg = SloRegistry(**kwargs)
    _SLO = reg
    SCHED_SLO_HOOK = reg
    ENGINE_SLO_HOOK = reg
    ROUTER_SLO_HOOK = reg
    _events.record("slo.capture_start", "slo accounting enabled")
    return reg


def disable() -> None:
    global _SLO, SCHED_SLO_HOOK, ENGINE_SLO_HOOK, ROUTER_SLO_HOOK
    if _SLO is not None:
        _events.record("slo.capture_stop", "slo accounting disabled")
    _SLO = None
    SCHED_SLO_HOOK = None
    ENGINE_SLO_HOOK = None
    ROUTER_SLO_HOOK = None


def set_objective(tenant: str, *, p99_ms: Optional[float] = None,
                  goodput_ratio: Optional[float] = None) -> None:
    reg = _SLO
    if reg is None:
        raise RuntimeError("slo is not enabled; call slo.enable() first")
    reg.set_objective(tenant, p99_ms=p99_ms, goodput_ratio=goodput_ratio)


def snapshot() -> Dict[str, Any]:
    reg = _SLO
    if reg is None:
        return {"enabled": False, "tenants": {}}
    return reg.snapshot()


def push_data() -> Optional[Dict[str, Any]]:
    """Compact snapshot for the fleet push doc; None while disabled."""
    reg = _SLO
    if reg is None:
        return None
    return reg.snapshot()


def trace_points() -> List[Dict[str, Any]]:
    reg = _SLO
    if reg is None:
        return []
    return reg.trace_points()


def report() -> str:
    reg = _SLO
    if reg is None:
        return "slo: off"
    return reg.report()


def parse_slo_spec(text: str) -> Dict[str, Dict[str, float]]:
    """Parse ``TENANT:p99=50:goodput=0.99[,TENANT2:...]`` into objectives.

    Returns ``{tenant: {"p99_ms": ..., "goodput_ratio": ...}}`` with each
    tenant carrying at least one objective.  Raises ValueError on malformed
    specs, duplicate tenants, or out-of-range values.
    """
    out: Dict[str, Dict[str, float]] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            raise ValueError("empty --slo entry")
        fields = part.split(":")
        tenant = fields[0].strip()
        if not tenant:
            raise ValueError("missing tenant name in --slo entry %r" % part)
        if tenant in out:
            raise ValueError("duplicate tenant %r in --slo" % tenant)
        if len(fields) < 2:
            raise ValueError("tenant %r declares no objectives" % tenant)
        obj: Dict[str, float] = {}
        for field in fields[1:]:
            if "=" not in field:
                raise ValueError("bad objective %r (want key=value)" % field)
            key, _, val = field.partition("=")
            key = key.strip()
            try:
                num = float(val)
            except ValueError:
                raise ValueError("bad value in objective %r" % field)
            if key == "p99":
                if num <= 0:
                    raise ValueError("p99 must be > 0 in %r" % part)
                obj["p99_ms"] = num
            elif key == "goodput":
                if not (0.0 < num < 1.0):
                    raise ValueError("goodput must be in (0, 1) in %r" % part)
                obj["goodput_ratio"] = num
            else:
                raise ValueError("unknown objective key %r" % key)
        out[tenant] = obj
    return out


# Event helpers — this module owns the slo.* event-type literals so the
# nnslint event-layer-placement rule holds (health calls these lazily).

def event_burn_alert(component: str, data: Dict[str, Any]) -> None:
    _events.record(
        "slo.burn_alert",
        "SLO burn threshold breached for %s" % component,
        severity="warning",
        component=component,
        tenant=data.get("tenant"),
        worst_objective=data.get("worst_objective"),
        worst_burn=data.get("worst_burn"),
        breached_objectives=data.get("breached_objectives"),
    )
    # burn alerts are THE diag capture trigger — cold path, lazy
    # import keeps the obs package import graph acyclic
    from . import diag as _diag
    dhook = _diag.DIAG_HOOK
    if dhook is not None:
        dhook.on_burn_alert(component, data)


def event_burn_recover(component: str, data: Dict[str, Any]) -> None:
    _events.record(
        "slo.recover",
        "SLO burn recovered for %s" % component,
        component=component,
        tenant=data.get("tenant"),
    )


if os.environ.get("NNSTPU_SLO", "") == "1":
    enable()
