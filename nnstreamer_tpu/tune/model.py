"""Learned cost model over the profiler's persisted samples.

Per-``(device_kind, label)`` linear regression

    cost_us  ≈  a · flops  +  b · bytes  +  c

fit by closed-form least squares (3×3 normal equations via numpy —
no ML dependency, deterministic for a given sample set). The features
are exactly what ``obs/profile.py`` already records per dispatch:
XLA-reported FLOPs and traffic bytes, plus the measured device-or-host
microseconds. That makes the model a roofline with learned, per-device
coefficients: ``a`` ≈ 1/attainable-FLOPs, ``b`` ≈ 1/attainable-bytes,
``c`` the dispatch floor — the same decomposition "A Learned
Performance Model for TPUs" starts from before reaching for a GNN,
which sample counts here (tens per label, not millions) cannot feed.

Candidate ranking only needs *relative* cost under varying traffic, so
a label with too few or degenerate samples simply reports no coverage
and the tuner falls through to its measured sweep.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

#: minimum samples per (device, label) before a fit is attempted —
#: below this the normal equations are underdetermined noise
MIN_SAMPLES = 3


def _sample_rows(samples: Iterable[Dict[str, Any]]
                 ) -> Dict[Tuple[str, str], List[Tuple[float, float, float]]]:
    """Group profiler sample rows into (device, label) → [(flops,
    bytes, cost_us)]. Device timing is preferred; host timing is the
    fallback (CPU runs report no device counters)."""
    by_key: Dict[Tuple[str, str], List[Tuple[float, float, float]]] = {}
    for row in samples:
        label = row.get("label")
        device = row.get("device") or "unknown"
        if not label:
            continue
        cost = row.get("mean_device_us") or row.get("mean_host_us")
        if not cost or cost <= 0:
            continue
        flops = float(row.get("flops") or 0.0)
        nbytes = float(row.get("bytes") or 0.0)
        if flops <= 0 and nbytes <= 0:
            continue
        by_key.setdefault((str(device), str(label)), []).append(
            (flops, nbytes, float(cost)))
    return by_key


class CostModel:
    """Per-(device, label) linear fit with explicit coverage."""

    def __init__(self) -> None:
        #: (device, label) -> (a, b, c) with cost_us = a*flops+b*bytes+c
        self._coef: Dict[Tuple[str, str], Tuple[float, float, float]] = {}
        self.n_samples = 0

    def fit(self, samples: Iterable[Dict[str, Any]]) -> int:
        """Fit every (device, label) group with enough samples; returns
        the number of groups covered. Refitting replaces prior
        coefficients (the sample set is the source of truth)."""
        grouped = _sample_rows(samples)
        self._coef.clear()
        self.n_samples = sum(len(v) for v in grouped.values())
        for key, rows in grouped.items():
            if len(rows) < MIN_SAMPLES:
                continue
            arr = np.asarray(rows, dtype=np.float64)
            x = np.column_stack([arr[:, 0], arr[:, 1],
                                 np.ones(len(rows))])
            y = arr[:, 2]
            # lstsq handles rank deficiency (all-equal features) by the
            # min-norm solution — deterministic, and still usable for
            # ranking because the degenerate feature gets weight 0
            coef, *_ = np.linalg.lstsq(x, y, rcond=None)
            # a negative flops/bytes weight means the fit extrapolates
            # "more work is faster" — a sure sign the samples do not
            # span the feature; treat as no coverage rather than rank
            # candidates backwards
            if coef[0] < 0 or coef[1] < 0:
                continue
            self._coef[key] = (float(coef[0]), float(coef[1]),
                               float(coef[2]))
        return len(self._coef)

    def covers(self, device: str, label: str) -> bool:
        return (device, label) in self._coef

    def predict(self, device: str, label: str, flops: float,
                nbytes: float) -> Optional[float]:
        """Predicted cost in microseconds, or None without coverage."""
        coef = self._coef.get((device, label))
        if coef is None:
            return None
        a, b, c = coef
        return a * float(flops) + b * float(nbytes) + c

    def coverage(self) -> List[Tuple[str, str]]:
        return sorted(self._coef)
