"""tune/ — learned autotuner closing the profiler's measure→act loop.

``obs/profile.py`` records per-dispatch cost samples; this package
*acts* on them. A :class:`~nnstreamer_tpu.tune.tuner.Tuner` owns the
knobs that used to be hand-set — flash-attention block shapes, the LM
engine's prefill chunk and KV page size, the spec-decode draft length,
the XLA bucket-ladder rung, the router's hedge delay — and resolves
each from (in order) its persistent store, a cost model fit over the
profiler's samples, or a bounded measured sweep. Results persist keyed
by ``(device_kind, label, shape_sig)`` and federate through
``obs/fleet.py`` push docs, so a fleet pays any sweep once, ever.

Zero-overhead contract: every wired call site gates on the module
global :data:`TUNE_HOOK` exactly like the profiler hooks —

    tn = _tune.TUNE_HOOK
    if tn is not None:
        value = tn.pick(...)

one attribute load and a None test when tuning is off, and the tuned
value is whatever the site's hand-set default was. ``enable()`` /
``disable()`` are the only writers of the hook (enforced by nnslint's
tune rule).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .model import CostModel
from .store import TuneStore
from .tuner import Tuner, shape_sig

__all__ = ["TUNE_HOOK", "CostModel", "TuneStore", "Tuner", "shape_sig",
           "enable", "disable", "enabled", "tuner", "report",
           "snapshot", "device_kind"]

#: the None-gated autotuner hook. None (the default) means every wired
#: knob site uses its hand-set default at zero added cost; a
#: :class:`Tuner` here means sites resolve knobs through it. Assigned
#: only by :func:`enable`/:func:`disable` below (and obs/profile.py,
#: per the nnslint ownership rule).
TUNE_HOOK: Optional[Tuner] = None

#: default on-disk store when ``enable()`` gets no path: the CLI's
#: ``--tune`` bare form and the env transport both land here
DEFAULT_STORE_ENV = "NNSTPU_TUNE_STORE"
DEFAULT_STORE = ".nnstpu_tune.json"


def device_kind() -> str:
    """The store key's device axis: the default jax device's kind
    (``"TPU v4"``-style on real hardware, ``"cpu"`` under the CPU
    platform). Import-light and failure-tolerant — the tuner must key
    something even when jax is mid-initialisation."""
    try:
        import jax

        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", None)
                   or getattr(dev, "platform", "unknown"))
    except Exception:
        return "unknown"


def enable(store_path: Optional[str] = None, max_trials: int = 8,
           fit_from_profiler: bool = True) -> Tuner:
    """Build and install the process-global tuner.

    ``store_path`` None resolves through $NNSTPU_TUNE_STORE then the
    ``.nnstpu_tune.json`` default; the file is loaded when present
    (warm store → zero sweeps). When the live profiler already holds
    samples the cost model is fit from them immediately; either way
    the fleet hooks are installed so tuned configs ride push docs and
    push-acks.
    """
    global TUNE_HOOK
    if TUNE_HOOK is not None:
        return TUNE_HOOK
    path = store_path or os.environ.get(DEFAULT_STORE_ENV) \
        or DEFAULT_STORE
    tn = Tuner(store=TuneStore(path), max_trials=max_trials)
    if fit_from_profiler:
        try:
            from ..obs import profile as _profile

            rows = _profile.profiler().samples()
            if rows:
                tn.fit(rows)
        except Exception:
            pass
    # federation: the push doc carries the store, the push-ack merges
    # the fleet's — both None-gated module hooks on obs/fleet.py
    from ..obs import fleet as _fleet

    _fleet.TUNE_PUSH_HOOK = tn.push_doc
    _fleet.TUNE_ADOPT_HOOK = tn.adopt
    TUNE_HOOK = tn
    return tn


def disable(save: bool = True) -> None:
    """Uninstall the tuner and (by default) persist its store."""
    global TUNE_HOOK
    tn = TUNE_HOOK
    TUNE_HOOK = None
    from ..obs import fleet as _fleet

    _fleet.TUNE_PUSH_HOOK = None
    _fleet.TUNE_ADOPT_HOOK = None
    if tn is not None and save and tn.store.path and tn.store.dirty:
        try:
            tn.store.save()
        except OSError:
            pass


def enabled() -> bool:
    return TUNE_HOOK is not None


def tuner() -> Optional[Tuner]:
    return TUNE_HOOK


def snapshot() -> Optional[Dict[str, Any]]:
    """The ``/debug/tune`` payload (None when tuning is off)."""
    tn = TUNE_HOOK
    return None if tn is None else tn.snapshot()


def report() -> str:
    tn = TUNE_HOOK
    return "autotuner: off" if tn is None else tn.report()
