"""Persistent knob store: ``(device_kind, label, shape_sig, knob)`` →
chosen value.

The store is the autotuner's memory. Every resolution the
:class:`~nnstreamer_tpu.tune.tuner.Tuner` makes — a measured sweep, a
cost-model pick, or a fleet adoption — lands here keyed by where it is
valid: the device kind (block shapes tuned on one TPU generation do not
transfer to another), the dispatch label (the profiler's kernel/filter
identity), and a caller-supplied shape signature (the knob's value is
shape-dependent: a 2048-token flash dispatch wants different blocks
than an 8192-token one).

On-disk format (``version`` 1) is a flat JSON object so the fleet layer
can ship it verbatim inside push docs:

    {"version": 1,
     "entries": {"<device>|<label>|<sig>|<knob>":
                 {"value": ..., "source": "sweep|model|fleet|observed",
                  "cost_us": 12.3, "ts": 1700000000.0}}}

``value`` is any JSON scalar or list (callers coerce — e.g. the flash
site unpacks a 2-list back into ``(block_q, block_k)``). ``cost_us`` is
the measured/predicted cost of the chosen value when known; fleet
merges prefer the lower-cost entry when both sides know one.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, Optional

STORE_VERSION = 1

#: hard cap on entries shipped in one fleet push doc — the push body is
#: size-bounded (obs/fleet.py MAX_PUSH_BYTES); a store can grow without
#: bound locally but federation ships only the newest slice
MAX_PUSH_ENTRIES = 256


def key_of(device: str, label: str, shape_sig: str, knob: str) -> str:
    return f"{device}|{label}|{shape_sig}|{knob}"


class TuneStore:
    """Dict-of-records with atomic JSON persistence.

    Single-threaded by contract like the rest of the knob plumbing: the
    tuner consults it from dispatch sites, and the fleet adoption hook
    runs on the pusher thread — adoption therefore goes through
    :meth:`merge_doc`, which only ever replaces whole records (a dict
    swap, atomic under the GIL).
    """

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._entries: Dict[str, Dict[str, Any]] = {}
        self.dirty = False
        if path and os.path.exists(path):
            self.load(path)

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, device: str, label: str, shape_sig: str,
            knob: str) -> Optional[Dict[str, Any]]:
        return self._entries.get(key_of(device, label, shape_sig, knob))

    def put(self, device: str, label: str, shape_sig: str, knob: str,
            value: Any, source: str,
            cost_us: Optional[float] = None) -> Dict[str, Any]:
        rec = {"value": value, "source": source,
               "cost_us": None if cost_us is None else float(cost_us),
               "ts": time.time()}
        self._entries[key_of(device, label, shape_sig, knob)] = rec
        self.dirty = True
        return rec

    def entries(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._entries)

    # -- persistence ---------------------------------------------------- #
    def load(self, path: Optional[str] = None) -> int:
        p = path or self.path
        if not p:
            return 0
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("version") != STORE_VERSION:
            raise ValueError(
                f"tune store {p}: unsupported version {doc.get('version')!r}")
        ents = doc.get("entries")
        if isinstance(ents, dict):
            self._entries.update(
                {k: v for k, v in ents.items() if isinstance(v, dict)})
        self.dirty = False
        return len(self._entries)

    def save(self, path: Optional[str] = None) -> Optional[str]:
        p = path or self.path
        if not p:
            return None
        doc = {"version": STORE_VERSION, "entries": self._entries}
        # atomic replace: a crashed save never truncates the store a
        # warm restart was counting on
        d = os.path.dirname(os.path.abspath(p)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".tune-", dir=d)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, p)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.dirty = False
        return p

    # -- federation ----------------------------------------------------- #
    def to_doc(self) -> Dict[str, Any]:
        """The slice of the store a fleet push carries: newest-first,
        capped at :data:`MAX_PUSH_ENTRIES`."""
        items = sorted(self._entries.items(),
                       key=lambda kv: kv[1].get("ts") or 0.0,
                       reverse=True)[:MAX_PUSH_ENTRIES]
        return {"version": STORE_VERSION, "entries": dict(items)}

    def merge_doc(self, doc: Any) -> int:
        """Adopt entries from a fleet-shipped doc. A remote record wins
        only where this store has nothing for the key, or where the
        remote knows a strictly lower measured cost — a local sweep is
        never overwritten by a lossier remote pick. Returns how many
        records were adopted."""
        if not isinstance(doc, dict):
            return 0
        ents = doc.get("entries")
        if not isinstance(ents, dict):
            return 0
        n = 0
        for k, rec in ents.items():
            if not isinstance(rec, dict) or "value" not in rec:
                continue
            mine = self._entries.get(k)
            if mine is not None:
                rc, mc = rec.get("cost_us"), mine.get("cost_us")
                if rc is None or (mc is not None and rc >= mc):
                    continue
            self._entries[k] = {"value": rec["value"], "source": "fleet",
                                "cost_us": rec.get("cost_us"),
                                "ts": rec.get("ts") or time.time()}
            n += 1
        if n:
            self.dirty = True
        return n
