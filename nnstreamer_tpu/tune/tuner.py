"""Runtime :class:`Tuner`: the measure→act loop's act half.

Resolution order for every knob a hot path asks about, strictly
cheapest-first:

1. **Store hit** — the knob was tuned before (this process, a previous
   run via the on-disk store, or another fleet instance via adoption).
   Zero measurement; this is the steady state a warm fleet lives in.
2. **Cost-model pick** — the per-(device, label) regression fit over
   the profiler's persisted samples has coverage, and the caller
   supplied per-candidate features: rank candidates by predicted cost,
   persist the winner as ``source="model"``.
3. **Bounded measured sweep** — the caller supplied a ``measure``
   closure: time at most :attr:`Tuner.max_trials` candidates once,
   persist the winner as ``source="sweep"``. The bound is a hard cap,
   not a target — a fleet pays this once per (device, label, shape,
   knob), ever, because the result federates.
4. **The hand-set default** — exactly what the call site did before
   the tuner existed.

Call sites supply the ``measure`` closure themselves (the tuner never
imports ops/serving — no cycle, and only the site knows how to build a
representative dispatch). Every resolution is deterministic for a given
store + sample set: candidate order breaks cost ties.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

from ..obs import events as _events
from ..obs import metrics as _obs
from .model import CostModel
from .store import TuneStore

_reg = _obs.registry()
_PICKS = _reg.counter(
    "nnstpu_tune_picks_total",
    "Knob resolutions by how they were decided (store/model/sweep/"
    "default/fleet)", ("source",))
_TRIALS = _reg.counter(
    "nnstpu_tune_sweep_trials_total",
    "Individual measured-sweep trials run (bounded per knob by "
    "max_trials)")
_ADOPTED = _reg.counter(
    "nnstpu_tune_adopted_total",
    "Tuned configs adopted from fleet push-acks")


def shape_sig(*dims: Any) -> str:
    """Canonical shape signature: ``shape_sig(('b', 8), ('l', 2048))``
    → ``"b8.l2048"``. Keys keep sigs self-describing across knobs."""
    return ".".join(f"{k}{v}" for k, v in dims)


class Tuner:
    """Owns the store, the model, and the sweep budget.

    Installed as the module-global ``tune.TUNE_HOOK`` — hot paths pay
    one attribute load + None check when tuning is off, and call
    :meth:`pick` when it is on.
    """

    def __init__(self, store: Optional[TuneStore] = None,
                 model: Optional[CostModel] = None,
                 max_trials: int = 8,
                 measure_repeats: int = 3) -> None:
        self.store = store if store is not None else TuneStore()
        self.model = model if model is not None else CostModel()
        self.max_trials = max(int(max_trials), 1)
        self.measure_repeats = max(int(measure_repeats), 1)
        #: auto-arm QueryRouter hedging from observed P95 when no
        #: manual --hedge-ms floor was given (query/router.py gate)
        self.auto_hedge = True
        self.stats: Dict[str, int] = {
            "picks": 0, "store_hits": 0, "model_picks": 0, "sweeps": 0,
            "trials": 0, "defaults": 0, "adopted": 0, "observed": 0}

    # -- model feeding --------------------------------------------------- #
    def fit(self, samples: Iterable[Dict[str, Any]]) -> int:
        """(Re)fit the cost model from profiler sample rows
        (``obs.profile.Profiler.samples()`` or a persisted
        ``dump_samples`` file's ``samples`` list)."""
        return self.model.fit(samples)

    # -- the resolution -------------------------------------------------- #
    def pick(self, knob: str, device: str, label: str, sig: str,
             candidates: Sequence[Any], default: Any,
             measure: Optional[Callable[[Any], float]] = None,
             features: Optional[Callable[[Any], tuple]] = None) -> Any:
        """Resolve one knob. ``measure(candidate) -> seconds`` times one
        representative dispatch; ``features(candidate) -> (flops,
        bytes)`` feeds the cost model. Either may be None — the
        corresponding stage is skipped."""
        self.stats["picks"] += 1
        rec = self.store.get(device, label, sig, knob)
        if rec is not None:
            self.stats["store_hits"] += 1
            _PICKS.labels(rec.get("source") or "store").inc()
            return rec["value"]

        if features is not None and self.model.covers(device, label):
            best, best_cost = None, None
            for cand in candidates:
                try:
                    flops, nbytes = features(cand)
                except Exception:
                    continue
                cost = self.model.predict(device, label, flops, nbytes)
                if cost is not None and (best_cost is None
                                         or cost < best_cost):
                    best, best_cost = cand, cost
            if best is not None:
                self.stats["model_picks"] += 1
                _PICKS.labels("model").inc()
                self.store.put(device, label, sig, knob, best, "model",
                               cost_us=best_cost)
                return best

        if measure is not None:
            value = self._sweep(knob, device, label, sig, candidates,
                                default, measure)
            if value is not None:
                return value

        self.stats["defaults"] += 1
        _PICKS.labels("default").inc()
        return default

    def _sweep(self, knob: str, device: str, label: str, sig: str,
               candidates: Sequence[Any], default: Any,
               measure: Callable[[Any], float]) -> Optional[Any]:
        """Time at most ``max_trials`` candidates; persist and return
        the winner, or None when every trial failed (the caller falls
        back to its default, and nothing is persisted — a later call
        may retry)."""
        self.stats["sweeps"] += 1
        best, best_s = None, None
        trials = 0
        t0 = time.monotonic()
        for cand in candidates[:self.max_trials]:
            trials += 1
            self.stats["trials"] += 1
            _TRIALS.inc()
            try:
                s = min(measure(cand) for _ in range(self.measure_repeats))
            except Exception:
                continue
            if best_s is None or s < best_s:
                best, best_s = cand, s
        if best is None:
            return None
        _PICKS.labels("sweep").inc()
        self.store.put(device, label, sig, knob, best, "sweep",
                       cost_us=best_s * 1e6)
        _events.record(
            "tune.sweep",
            f"swept {knob} for {label} [{sig}] on {device}: "
            f"{best!r} at {best_s * 1e6:.1f}us "
            f"({trials} trials, {time.monotonic() - t0:.2f}s)",
            knob=knob, label=label, device=device, trials=trials)
        return best

    def observe(self, knob: str, device: str, label: str, sig: str,
                value: Any, cost_us: Optional[float] = None) -> None:
        """Record a knob value derived from live observation (e.g. the
        spec-decode draft length computed from the observed accept
        rate) so it persists and federates like a swept one."""
        self.stats["observed"] += 1
        _PICKS.labels("observed").inc()
        self.store.put(device, label, sig, knob, value, "observed",
                       cost_us=cost_us)

    # -- federation ------------------------------------------------------ #
    def push_doc(self) -> Optional[Dict[str, Any]]:
        """The tune layer of an outgoing fleet push doc (None when the
        store is empty — the push stays byte-identical to pre-tune)."""
        if not len(self.store):
            return None
        return self.store.to_doc()

    def adopt(self, doc: Any) -> int:
        """Merge a fleet-shipped tune doc (the ``tune`` field of a
        push-ack). Runs on the pusher thread — before the instance's
        first dispatch when fleet push is enabled at startup, which is
        exactly what lets a fresh instance skip its sweeps."""
        n = self.store.merge_doc(doc)
        if n:
            self.stats["adopted"] += n
            _ADOPTED.inc(n)
            _events.record("tune.adopt",
                           f"adopted {n} fleet-tuned config(s)", n=n)
        return n

    # -- reporting ------------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        return {"stats": dict(self.stats),
                "model_coverage": ["|".join(k)
                                   for k in self.model.coverage()],
                "store_path": self.store.path,
                "entries": self.store.entries()}

    def report(self) -> str:
        s = self.stats
        lines = [
            "autotuner:",
            f"  picks {s['picks']}  (store {s['store_hits']}, model "
            f"{s['model_picks']}, sweeps {s['sweeps']} / "
            f"{s['trials']} trials, defaults {s['defaults']})",
            f"  adopted from fleet: {s['adopted']}   observed: "
            f"{s['observed']}",
            f"  store: {len(self.store)} entr"
            f"{'y' if len(self.store) == 1 else 'ies'}"
            + (f" -> {self.store.path}" if self.store.path else ""),
        ]
        for k, rec in sorted(self.store.entries().items()):
            cost = rec.get("cost_us")
            lines.append(
                f"    {k} = {rec['value']!r} [{rec['source']}"
                + (f", {cost:.1f}us" if cost is not None else "") + "]")
        return "\n".join(lines)
