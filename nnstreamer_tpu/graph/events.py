"""In-band events and bus messages for the pipeline runtime.

GStreamer equivalent: GstEvent (serialized in-band with buffers: CAPS before
first data, EOS at end, FLUSH) and GstMessage (out-of-band bus to the app).
QoS events travel *upstream* (sink→src) — tensor_rate uses them to throttle
tensor_filter (reference: gsttensorrate.c QoS + tensor_filter.c:425-480).
"""

from __future__ import annotations

import enum
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class EventType(enum.Enum):
    STREAM_START = "stream-start"
    CAPS = "caps"
    SEGMENT = "segment"
    EOS = "eos"
    FLUSH = "flush"
    QOS = "qos"                    # upstream: throttling request
    RELOAD_MODEL = "reload-model"  # custom: tensor_filter hot swap (nnstreamer_plugin_api_filter.h:377-383)
    CUSTOM = "custom"


@dataclass
class Event:
    type: EventType
    data: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def caps(cls, caps: Any) -> "Event":
        return cls(EventType.CAPS, {"caps": caps})

    @classmethod
    def eos(cls) -> "Event":
        return cls(EventType.EOS)

    @classmethod
    def qos(cls, *, interval_ns: int) -> "Event":
        """Throttle request: upstream should emit at most one buffer per
        interval_ns (tensor_rate → tensor_filter contract)."""
        return cls(EventType.QOS, {"interval_ns": interval_ns})

    @classmethod
    def reload_model(cls, model: Any) -> "Event":
        return cls(EventType.RELOAD_MODEL, {"model": model})


class MessageType(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    EOS = "eos"
    STATE_CHANGED = "state-changed"
    ELEMENT = "element"  # element-specific (e.g. tensor_sink stats)


@dataclass
class Message:
    type: MessageType
    source: str
    data: Dict[str, Any] = field(default_factory=dict)


class Bus:
    """Out-of-band message channel from elements to the app/pipeline."""

    def __init__(self) -> None:
        self._q: "queue.Queue[Message]" = queue.Queue()
        self._eos = threading.Event()
        self._error: Optional[Message] = None
        self._lock = threading.Lock()

    def post(self, msg: Message) -> None:
        if msg.type is MessageType.EOS:
            self._eos.set()
        elif msg.type is MessageType.ERROR:
            with self._lock:
                if self._error is None:
                    self._error = msg
            self._eos.set()  # error terminates waits too
        self._q.put(msg)

    def pop(self, timeout: Optional[float] = 0) -> Optional[Message]:
        try:
            return self._q.get(timeout=timeout) if timeout else self._q.get_nowait()
        except queue.Empty:
            return None

    @property
    def error(self) -> Optional[Message]:
        with self._lock:
            return self._error

    def wait_eos(self, timeout: Optional[float] = None) -> bool:
        return self._eos.wait(timeout)

    def clear(self) -> None:
        self._eos.clear()
        with self._lock:
            self._error = None
        while self.pop():
            pass
