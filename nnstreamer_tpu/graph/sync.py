"""Time-synchronization policies for N-input collection (mux/merge).

Equivalent of the reference's sync engine (tensor_common.h:62-69 policies
NOSYNC/SLOWEST/BASEPAD/REFRESH; logic tensor_common_pipeline.c; documented in
Documentation/synchronization-policies-at-mux-merge.md):

  * ``nosync``  — combine in arrival order: emit when every pad has a buffer.
  * ``slowest`` — sync on the slowest pad: base PTS = max of head PTS across
    pads; older buffers on faster pads are dropped (keep nearest ≤ base).
  * ``basepad`` — base PTS from a designated pad (option "idx:duration_ns");
    other pads pick their buffer nearest the base within the duration window.
  * ``refresh`` — emit on every new arrival on any pad, re-using the last
    seen buffer of the other pads.

``CollectPads`` is the GstCollectPads stand-in: per-pad FIFOs + a policy that
yields ready frame-sets. Thread-safe; chain calls may arrive from multiple
streaming threads.
"""

from __future__ import annotations

import collections
import enum
import threading
from typing import Deque, Dict, List, Optional, Tuple

from ..core.buffer import Buffer


class SyncPolicy(enum.Enum):
    NOSYNC = "nosync"
    SLOWEST = "slowest"
    BASEPAD = "basepad"
    REFRESH = "refresh"

    @classmethod
    def parse(cls, s) -> "SyncPolicy":
        if isinstance(s, SyncPolicy):
            return s
        return cls(str(s).strip().lower())


def _pts(buf: Buffer) -> int:
    return buf.pts if buf.pts is not None else 0


class CollectPads:
    """Collects buffers from N named inputs and yields synchronized sets.

    ``push(key, buf)`` returns a list of ready sets; each set is a dict
    ``key → Buffer`` plus the chosen output PTS. ``set_eos(key)`` marks an
    input finished; ``exhausted`` turns True when no further set can ever be
    produced (mux forwards EOS then).
    """

    def __init__(self, keys: List[str], policy: SyncPolicy = SyncPolicy.SLOWEST,
                 base_key: Optional[str] = None, base_duration_ns: int = 0):
        self.keys = list(keys)
        self.policy = policy
        self.base_key = base_key if base_key is not None else (self.keys[0] if self.keys else None)
        self.base_duration_ns = base_duration_ns
        self._queues: Dict[str, Deque[Buffer]] = {k: collections.deque() for k in self.keys}
        self._last: Dict[str, Optional[Buffer]] = {k: None for k in self.keys}
        self._eos: Dict[str, bool] = {k: False for k in self.keys}
        self._lock = threading.Lock()

    def add_key(self, key: str) -> None:
        with self._lock:
            self.keys.append(key)
            self._queues[key] = collections.deque()
            self._last[key] = None
            self._eos[key] = False
            if self.base_key is None:
                self.base_key = key

    # ------------------------------------------------------------------ #
    def push(self, key: str, buf: Buffer) -> List[Tuple[Dict[str, Buffer], Optional[int]]]:
        with self._lock:
            self._queues[key].append(buf)
            self._last[key] = buf
            out = []
            while True:
                s = self._try_collect(trigger=key)
                if s is None:
                    break
                out.append(s)
                if self.policy is SyncPolicy.REFRESH:
                    break  # refresh emits exactly once per arrival
            return out

    def set_eos(self, key: str) -> List[Tuple[Dict[str, Buffer], Optional[int]]]:
        with self._lock:
            self._eos[key] = True
            out = []
            while True:
                s = self._try_collect(trigger=None)
                if s is None:
                    break
                out.append(s)
            return out

    @property
    def exhausted(self) -> bool:
        """No further output possible: some pad is EOS with an empty queue
        (refresh: all pads EOS)."""
        with self._lock:
            if self.policy is SyncPolicy.REFRESH:
                return all(self._eos.values())
            return any(self._eos[k] and not self._queues[k] for k in self.keys)

    # ------------------------------------------------------------------ #
    def _try_collect(self, trigger: Optional[str]):
        if self.policy is SyncPolicy.REFRESH:
            if trigger is None:
                return None
            if all(self._last[k] is not None for k in self.keys):
                s = {k: self._last[k] for k in self.keys}
                # consume the trigger buffer; others stay as "last"
                if self._queues[trigger]:
                    self._queues[trigger].popleft()
                return s, _pts(s[trigger])
            if self._queues[trigger]:
                self._queues[trigger].popleft()  # buffered as last already
            return None

        live = [k for k in self.keys if not (self._eos[k] and not self._queues[k])]
        if len(live) < len(self.keys):
            # a pad is finished: no complete set can form (caller checks
            # `exhausted` and forwards EOS)
            return None
        if not all(self._queues[k] for k in self.keys):
            return None

        if self.policy is SyncPolicy.NOSYNC:
            s = {k: self._queues[k].popleft() for k in self.keys}
            return s, _pts(s[self.keys[0]])

        if self.policy is SyncPolicy.SLOWEST:
            base = max(_pts(q[0]) for q in self._queues.values() if q)
        else:  # BASEPAD
            base = _pts(self._queues[self.base_key][0])

        window = self.base_duration_ns
        chosen: Dict[str, Buffer] = {}
        for k in self.keys:
            q = self._queues[k]
            # drop stale buffers strictly older than base (outside window)
            while len(q) > 1 and _pts(q[0]) + window < base and _pts(q[1]) <= base:
                q.popleft()
            if not q:
                return None
            chosen[k] = q[0]
        for k in self.keys:
            self._queues[k].popleft()
        return chosen, base
