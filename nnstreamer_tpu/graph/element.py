"""Element/Pad model — the pipeline's structural core.

This re-implements, TPU-framework-style, what the reference gets from
GStreamer (GstElement/GstPad/GstBaseTransform): typed pads, caps negotiation
via in-band CAPS events, push-mode dataflow, EOS propagation, and upstream
QoS events. Elements are single-responsibility nodes; heavy math lives in
XLA-compiled functions the elements dispatch to, so Python-side work per
buffer is bookkeeping only (the GIL is released inside XLA dispatch).

Flow model (simplified from GStreamer, same semantics for our graphs):
  * src pad ``push(buffer)`` → peer sink pad → owner ``chain(pad, buffer)``.
  * events travel in-band downstream (STREAM_START, CAPS, EOS, FLUSH) or
    upstream (QOS, RELOAD_MODEL) via ``push_event``.
  * a chain error posts an ERROR bus message and returns FlowReturn.ERROR
    upstream, stopping sources (GST_FLOW_ERROR; tensor_filter.c:494-520).
  * invoke soft-failure: an element may *drop* a buffer by returning
    normally without pushing (reference ret>0 drop, tensor_filter.c:702-705).
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..core.buffer import Buffer
from ..core.types import Caps
from ..core.log import logger
from ..obs import events as _events
from ..obs import quality as _quality
from .events import Bus, Event, EventType, Message, MessageType

log = logger("element")

def join_or_warn(t: threading.Thread, owner: str,
                 timeout: float = 5.0) -> bool:
    """Join a worker thread with a bounded wait; a timeout logs a
    WARNING and records a ``pipeline.thread_leak`` event instead of
    abandoning the thread invisibly (a leaked daemon worker keeps its
    element state alive and can wake on a reused port/queue later).
    Returns True when the thread actually exited."""
    t.join(timeout=timeout)
    if not t.is_alive():
        return True
    log.warning("%s: thread %r did not exit within %.1fs — leaked",
                owner, t.name, timeout)
    _events.record("pipeline.thread_leak",
                   f"{owner}: thread {t.name!r} did not exit within "
                   f"{timeout:.1f}s", severity="warning",
                   element=owner, thread=t.name)
    return False


#: chaos injection point (resilience/chaos.py installs/clears this):
#: called as ``hook(sink_element_name, buf) -> bool`` before the peer's
#: chain; True drops the buffer (the graph's legal drop semantics —
#: return OK without delivering), a raise rides the existing chain-error
#: path onto the bus. Disabled cost: one global load + None check.
CHAOS_CHAIN_HOOK = None

#: profiler timing point (obs/profile.py installs/clears this): called
#: as ``hook(peer_pad, buf)`` IN PLACE of ``peer.element._chain_entry``
#: — it runs the chain itself, timed, and returns the chain's
#: FlowReturn. Same disabled cost contract as CHAOS_CHAIN_HOOK.
PROFILE_CHAIN_HOOK = None


class FlowReturn(enum.Enum):
    OK = "ok"
    EOS = "eos"
    ERROR = "error"
    FLUSHING = "flushing"


class PadDirection(enum.Enum):
    SRC = "src"
    SINK = "sink"


class Pad:
    def __init__(self, element: "Element", name: str, direction: PadDirection,
                 template: Optional[Caps] = None):
        self.element = element
        self.name = name
        self.direction = direction
        self.template = template
        self.peer: Optional["Pad"] = None
        self.caps: Optional[Caps] = None  # negotiated
        self.eos = False

    @property
    def full_name(self) -> str:
        return f"{self.element.name}.{self.name}"

    # -- linking ------------------------------------------------------------ #
    def link(self, sink: "Pad") -> None:
        if self.direction is not PadDirection.SRC or sink.direction is not PadDirection.SINK:
            raise ValueError(f"link must be src→sink: {self.full_name}→{sink.full_name}")
        if self.peer is not None or sink.peer is not None:
            raise ValueError(f"pad already linked: {self.full_name} or {sink.full_name}")
        if self.template is not None and sink.template is not None \
                and self.template.intersect(sink.template) is None:
            raise ValueError(
                f"incompatible pad templates: {self.full_name}({self.template}) vs "
                f"{sink.full_name}({sink.template})")
        self.peer = sink
        sink.peer = self

    # -- dataflow ----------------------------------------------------------- #
    def push(self, buf: Buffer) -> FlowReturn:
        """Push a buffer from this SRC pad to the linked sink pad."""
        peer = self.peer
        if peer is None:
            return FlowReturn.ERROR
        if peer.eos:
            return FlowReturn.EOS
        try:
            if CHAOS_CHAIN_HOOK is not None \
                    and CHAOS_CHAIN_HOOK(peer.element.name, buf):
                return FlowReturn.OK  # buffer dropped by the fault plan
            # data-plane quality tap (obs/quality): observes the buffer
            # the peer actually receives — after chaos, so an injected
            # corruption is visible to the NaN-storm rule
            qhook = _quality.QUALITY_HOOK
            if qhook is not None:
                qhook.observe_chain(peer.element.name, buf)
            if PROFILE_CHAIN_HOOK is not None:
                ret = PROFILE_CHAIN_HOOK(peer, buf)
            else:
                ret = peer.element._chain_entry(peer, buf)
            return ret if ret is not None else FlowReturn.OK
        except Exception as e:  # noqa: BLE001 — element errors become bus messages
            peer.element.post_error(f"chain error: {type(e).__name__}: {e}", exc=e)
            return FlowReturn.ERROR

    def push_event(self, event: Event) -> None:
        """Send an in-band event downstream (SRC pad) or upstream (SINK pad)."""
        peer = self.peer
        if peer is None:
            return
        if self.direction is PadDirection.SRC:
            peer.element._event_entry(peer, event)
        else:
            peer.element._upstream_event_entry(peer, event)


class Element:
    """Base element. Subclasses declare pads in __init__ and override
    ``chain`` / ``on_caps`` / ``handle_event`` / ``start`` / ``stop``."""

    ELEMENT_NAME = "element"
    _instance_counter: Dict[str, int] = {}
    _counter_lock = threading.Lock()

    def __init__(self, name: Optional[str] = None, **props: Any):
        if name is None:
            with Element._counter_lock:
                n = Element._instance_counter.get(self.ELEMENT_NAME, 0)
                Element._instance_counter[self.ELEMENT_NAME] = n + 1
            name = f"{self.ELEMENT_NAME}{n}"
        self.name = name
        self.sink_pads: List[Pad] = []
        self.src_pads: List[Pad] = []
        self.bus: Optional[Bus] = None  # set by Pipeline.add
        self.pipeline: Optional[Any] = None
        self.started = False
        self._quitting = False  # set by Pipeline.stop's pre-pass
        #: scheduler executor (sched.DeviceEngine attach): None on the
        #: un-scheduled path — consumers gate on it, so the default hot
        #: path pays one attribute None check (same contract as the
        #: CHAOS/PROFILE chain hooks above)
        self._sched_exec = None
        self._lock = threading.RLock()
        self._eos_pads: set = set()
        self._unknown_props = {}
        self.set_properties(**props)

    # -- properties --------------------------------------------------------- #
    #: universally-accepted gst no-op props: every GstElement/BaseSink has
    #: these and the reference's SSAT strings set them freely (silent=TRUE,
    #: filesink sync=true …); they carry no behavior here but must not
    #: fail verbatim pipeline strings. Elements with real semantics for
    #: one (e.g. tensor_rate silent) simply shadow it with an attribute.
    # gst scheduling/buffering knobs with no analog in this runtime
    # (every sink here is already unbuffered and clock-free)
    _GST_NOOP_PROPS = frozenset({"silent", "sync", "async", "qos", "buffer_mode"})

    def set_properties(self, **props: Any) -> None:
        """GObject-property equivalent: kwargs map to attributes. Unknown
        properties raise (reference: malformed props must fail; SSAT negative
        tests rely on this)."""
        for k, v in props.items():
            attr = k.replace("-", "_")
            setter = getattr(self, f"_set_prop_{attr}", None)
            if setter is not None:
                setter(v)
            elif hasattr(self, attr) and not attr.startswith("_"):
                setattr(self, attr, v)
            elif attr in self._GST_NOOP_PROPS:
                setattr(self, attr, v)
            else:
                raise ValueError(f"{self.ELEMENT_NAME}: unknown property {k!r}")

    # -- pad management ----------------------------------------------------- #
    def add_sink_pad(self, name: str = "sink", template: Optional[Caps] = None) -> Pad:
        pad = Pad(self, name, PadDirection.SINK, template)
        self.sink_pads.append(pad)
        return pad

    def add_src_pad(self, name: str = "src", template: Optional[Caps] = None) -> Pad:
        pad = Pad(self, name, PadDirection.SRC, template)
        self.src_pads.append(pad)
        return pad

    def free_sink_pad(self) -> Pad:
        """First unlinked sink pad, requesting a new one if none (the
        link-time pad selection shared by Pipeline.link and the textual
        parser)."""
        pad = next((q for q in self.sink_pads if q.peer is None), None)
        return pad if pad is not None else self.request_sink_pad()

    def free_src_pad(self) -> Pad:
        """First unlinked src pad, requesting a new one if none."""
        pad = next((q for q in self.src_pads if q.peer is None), None)
        return pad if pad is not None else self.request_src_pad()

    def request_sink_pad(self) -> Pad:
        """For N-input elements (mux/merge/join): new sink pad on demand."""
        return self.add_sink_pad(f"sink_{len(self.sink_pads)}")

    def request_src_pad(self) -> Pad:
        """For N-output elements (tee/demux/split): new src pad on demand."""
        return self.add_src_pad(f"src_{len(self.src_pads)}")

    @property
    def sink_pad(self) -> Pad:
        return self.sink_pads[0]

    @property
    def src_pad(self) -> Pad:
        return self.src_pads[0]

    @property
    def is_source(self) -> bool:
        return not self.sink_pads

    @property
    def is_sink(self) -> bool:
        return not self.src_pads

    # -- lifecycle ---------------------------------------------------------- #
    def prepare(self) -> None:
        """Pre-start phase: Pipeline.start calls this on EVERY element
        before ANY element's start() runs (so before any source thread
        exists). Reset process-global state here (e.g. repo slots) —
        doing it in start()/negotiate() would race already-running
        producers."""

    def start(self) -> None:  # override for resource acquisition
        pass

    def stop(self) -> None:  # override for teardown
        pass

    def request_stop(self) -> None:
        """Pre-stop broadcast: Pipeline.stop calls this on EVERY element
        BEFORE joining any thread, so chain()s blocked inside another
        element (rendezvous slots, backpressure waits) can bail out
        promptly instead of stalling the source joins. Overrides should
        call super() and wake their condition variables."""
        self._quitting = True

    # -- scheduler opt-in (sched/engine.py DeviceEngine.attach_pipeline) ---- #
    def sched_enroll(self, engine: Any, tenant: Any) -> None:
        """Offered to every element when its pipeline attaches to a
        DeviceEngine. Base elements have no device work to route —
        tensor_filter overrides to install ``self._sched_exec`` so its
        invokes coalesce across tenants. Must be idempotent."""

    def sched_detach(self) -> None:
        """Inverse of ``sched_enroll``: back to direct dispatch."""
        self._sched_exec = None

    # -- entry points (locking + dispatch) ----------------------------------- #
    def _chain_entry(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        return self.chain(pad, buf)

    def _event_entry(self, pad: Pad, event: Event) -> None:
        if event.type is EventType.CAPS:
            self.on_caps(pad, event.data["caps"])
            return
        if event.type is EventType.EOS:
            with self._lock:
                pad.eos = True
                self._eos_pads.add(pad.name)
                all_eos = len(self._eos_pads) >= len(self.sink_pads)
            if all_eos:
                try:
                    self.on_eos()
                except Exception as e:  # noqa: BLE001 — any flush failure
                    # must surface on the bus, and EOS must still propagate,
                    # or downstream never terminates and run() hits timeout
                    self.post_error(f"eos flush error: {type(e).__name__}: {e}",
                                    exc=e)
                if self.is_sink:
                    self.post_message(MessageType.ELEMENT, {"event": "eos"})
                    if self.pipeline is not None:
                        self.pipeline._sink_eos(self)
                else:
                    self.push_event_all(Event.eos())
            return
        self.handle_event(pad, event)

    def _upstream_event_entry(self, pad: Pad, event: Event) -> None:
        self.handle_upstream_event(pad, event)

    # -- vmethods ------------------------------------------------------------ #
    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        """Process one buffer arriving on ``pad``. Default: passthrough."""
        return self.push(buf)

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        """Handle CAPS on a sink pad. Default: passthrough caps downstream."""
        pad.caps = caps
        self.send_caps_all(caps)

    def on_eos(self) -> None:
        """Called once when all sink pads reached EOS (before forwarding)."""

    def handle_event(self, pad: Pad, event: Event) -> None:
        """Non-CAPS/EOS downstream events. Default: forward."""
        self.push_event_all(event)

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        """Upstream events (QOS, RELOAD_MODEL). Default: forward further up."""
        for sp in self.sink_pads:
            sp.push_event(event)

    # -- helpers ------------------------------------------------------------- #
    def push(self, buf: Buffer, pad_index: int = 0) -> FlowReturn:
        if not self.src_pads:
            return FlowReturn.OK
        return self.src_pads[pad_index].push(buf)

    def push_event_all(self, event: Event) -> None:
        for sp in self.src_pads:
            sp.push_event(event)

    def send_caps(self, caps: Caps, pad_index: int = 0) -> None:
        if self.src_pads:
            pad = self.src_pads[pad_index]
            pad.caps = caps
            pad.push_event(Event.caps(caps))

    def send_caps_all(self, caps: Caps) -> None:
        for i in range(len(self.src_pads)):
            self.send_caps(caps, i)

    def post_message(self, mtype: MessageType, data: Optional[dict] = None) -> None:
        if self.bus is not None:
            self.bus.post(Message(mtype, self.name, data or {}))

    def post_error(self, text: str, exc: Optional[BaseException] = None) -> None:
        log.error("[%s] %s", self.name, text, exc_info=exc)
        # flight recorder (obs/events.py, one flag check while off):
        # recorded from an instrumented chain this carries the failing
        # buffer's trace id via the current-context stamp
        _events.record("pipeline.error", f"{self.name}: {text}",
                       severity="error", element=self.name)
        if self.bus is not None:
            self.bus.post(Message(MessageType.ERROR, self.name,
                                  {"text": text, "exception": exc}))

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


# --------------------------------------------------------------------------- #
# Element class registry (for the textual pipeline parser / gst-launch CLI)
# --------------------------------------------------------------------------- #

_element_classes: Dict[str, type] = {}


def register_element(cls: type) -> type:
    """Class decorator: register under cls.ELEMENT_NAME (the reference's
    element registration in registerer/nnstreamer.c:88-114)."""
    _element_classes[cls.ELEMENT_NAME] = cls
    return cls


def element_class(name: str) -> Optional[type]:
    if name not in _element_classes:
        # lazily pull in built-ins on first miss
        from .. import _register_builtins

        _register_builtins()
    return _element_classes.get(name)


def make_element(name: str, element_name: Optional[str] = None, **props: Any) -> Element:
    cls = element_class(name)
    if cls is None:
        raise ValueError(f"unknown element type {name!r}")
    return cls(name=element_name, **props)


def all_element_names() -> List[str]:
    from .. import _register_builtins

    _register_builtins()
    return sorted(_element_classes)
