"""gst-launch-style textual pipeline parser.

Lets reference pipelines run near-verbatim:

    videotestsrc num-buffers=10 ! tensor_converter !
    tensor_transform mode=arithmetic option=typecast:float32,div:255.0 !
    tensor_filter framework=xla-tpu model=zoo://mobilenet_v2 !
    tensor_decoder mode=image_labeling option1=labels.txt ! tensor_sink

Supported grammar (the subset the reference's pipelines use):
  * ``elem prop=val prop2="quoted val" ! elem2 ...``
  * named elements + back-references: ``tee name=t ! ... t. ! queue ! ...``
    (segments separated by whitespace after a complete branch)
  * caps filter segments: ``video/x-raw,format=RGB,width=640,height=480`` or
    ``other/tensors,dimensions=...,types=...`` become CapsFilter elements
  * numbers/bools auto-typed; fractions stay strings ("30/1" → element-parsed)
"""

from __future__ import annotations

import re
import shlex
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

from ..core.types import ANY, Caps, TensorFormat
from .element import Element, FlowReturn, Pad, make_element, register_element
from .pipeline import Pipeline


@register_element
class CapsFilter(Element):
    """Pass-through that constrains negotiation (gst capsfilter)."""

    ELEMENT_NAME = "capsfilter"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.caps: Optional[Caps] = None
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        if self.caps is not None:
            merged = caps.intersect(self.caps)
            if merged is None:
                raise ValueError(
                    f"capsfilter: stream {caps} incompatible with {self.caps}")
            caps = merged
        pad.caps = caps
        self.send_caps_all(caps)


_MEDIA_TYPES = ("video/x-raw", "audio/x-raw", "text/x-raw",
                "application/octet-stream", "other/tensor", "other/tensors",
                "other/flexbuf", "other/flatbuf", "other/protobuf")

_INT_FIELDS = {"width", "height", "channels", "rate", "num"}


def _split_caps_fields(s: str) -> List[str]:
    """Split caps on commas outside double quotes (GStreamer quoting for
    values containing commas, e.g. multi-tensor dimension strings)."""
    parts, cur, quoted = [], [], False
    for ch in s:
        if ch == '"':
            quoted = not quoted
            cur.append(ch)
        elif ch == "," and not quoted:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def parse_caps_string(s: str) -> Caps:
    """"video/x-raw,format=RGB,width=640" → Caps."""
    parts = _split_caps_fields(s)
    media = parts[0].strip()
    if media == "other/tensor":
        media = "other/tensors"
    fields: Dict[str, Any] = {}
    for kv in parts[1:]:
        kv = kv.strip()
        if not kv:
            continue
        if "=" not in kv:
            raise ValueError(f"bad caps field {kv!r} in {s!r}")
        k, v = kv.split("=", 1)
        k = k.strip()
        v = re.sub(r"^\(\w+\)", "", v.strip())  # drop "(int)3" annotations
        v = v.strip('"')
        if k in ("dimensions", "dimension"):
            k = "dims"
        elif k == "type":  # other/tensor singular field names
            k = "types"
        elif k in ("num_tensors",):
            k = "num"
        if k in _INT_FIELDS:
            fields[k] = int(v)
        elif k == "framerate":
            n, d = (v.split("/") + ["1"])[:2]
            fields[k] = Fraction(int(n), int(d))
        elif k == "format" and media == "other/tensors":
            fields[k] = TensorFormat.parse(v)
        else:
            fields[k] = v
    return Caps(media, fields)


def _auto_type(v: str) -> Any:
    if re.fullmatch(r"-?\d+", v):
        return int(v)
    if re.fullmatch(r"0[xX][0-9a-fA-F]+", v):
        return int(v, 16)  # gst hex props, e.g. videotestsrc color=0xFF0000
    if re.fullmatch(r"-?\d*\.\d+([eE]-?\d+)?", v):
        return float(v)
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def parse_pipeline(description: str, pipeline: Optional[Pipeline] = None) -> Pipeline:
    """Build (and return) a Pipeline from a textual description."""
    if not description.strip():
        raise ValueError("empty pipeline description")
    p = pipeline or Pipeline()
    branches = _split_branches(description)
    named: Dict[str, Element] = {}
    # gst-launch allows "… ! mux.sink_0" before "tensor_mux name=mux" is
    # declared; every sink-side named link is deferred and resolved once
    # all branches are parsed, in encounter order, so request pads are
    # created in index order regardless of where the declaration sits
    pending: List[tuple] = []

    for branch in branches:
        prev: Optional[Any] = None
        prev_explicit: set = set()
        closed = False  # chain already sank into a named element/pad
        for seg in branch:
            if closed:
                raise ValueError(
                    "cannot continue a chain after linking into a named "
                    f"element/pad (dangling segment {seg!r})")
            if isinstance(seg, str):  # "name." or pad ref "name.sink_0"
                if seg.endswith("."):
                    ref = seg.rstrip(".")
                    if prev is not None:
                        # "… ! name." links INTO the named element's next
                        # free sink pad and ends the chain (gst-launch);
                        # ALWAYS deferred so request-pad creation follows
                        # global encounter order even when some references
                        # precede the declaration and some follow it
                        pending.append((prev, ref, None, seg))
                        prev = None
                        closed = True
                        continue
                    if ref not in named:
                        raise ValueError(
                            f"unknown element reference {seg!r}")
                    prev = named[ref]
                    # restore the referenced element's own explicit
                    # props — a following caps filter must respect them
                    prev_explicit = getattr(prev, "_parse_explicit", set())
                    continue
                ref, pad_name = seg.split(".", 1)
                if prev is not None:
                    # chain sinks INTO this pad: ... ! mux.sink_0 (deferred,
                    # see above)
                    pending.append((prev, ref, pad_name, seg))
                    prev = None
                    closed = True
                    continue
                if ref not in named:
                    # a branch STARTING at an unseen src pad cannot be
                    # deferred (everything after it would dangle)
                    raise ValueError(f"unknown element reference {seg!r}")
                # branch starts AT this src pad: demux.src_0 ! ...
                prev = (named[ref], pad_name)
                prev_explicit = set()
                continue
            kind, props = seg
            if kind in _MEDIA_TYPES or kind.split(",")[0] in _MEDIA_TYPES:
                caps = parse_caps_string(_reassemble_caps(kind, props))
                el = CapsFilter(caps=caps)
                p.add(el)
                _configure_upstream_from_caps(prev, caps, prev_explicit)
                explicit = set()
            else:
                name = props.pop("name", None)
                explicit = {k.replace("-", "_") for k in props}
                el = make_element(kind, element_name=name, **props)
                el._parse_explicit = explicit
                p.add(el)
                if name:
                    named[name] = el
            if prev is not None:
                _link(prev, el)
            prev = el
            prev_explicit = explicit

    for prev, ref, pad_name, seg in pending:
        if ref not in named:
            raise ValueError(f"unknown element reference {seg!r}")
        _link(prev, named[ref] if pad_name is None else (named[ref], pad_name))
    return p


def _link(src_spec: Any, dst_spec: Any) -> None:
    """Link with optional explicit pads: either side may be an Element
    (first-free-pad semantics, shared with Pipeline.link) or an
    ``(element, pad_name)`` tuple from a gst ``name.sink_0`` reference."""
    src = _pad_by_name(*src_spec, "src") if isinstance(src_spec, tuple) \
        else src_spec.free_src_pad()
    sink = _pad_by_name(*dst_spec, "sink") if isinstance(dst_spec, tuple) \
        else dst_spec.free_sink_pad()
    src.link(sink)


def _pad_by_name(el: Element, pad_name: str, direction: str) -> Any:
    """Resolve ``sink_N``/``src_N``. Request pads are created strictly in
    index order — referencing ``sink_1`` before ``sink_0`` would fabricate
    an unlinked lower pad that stalls collect elements forever, so a
    skipped index is an error instead."""
    pads = el.sink_pads if direction == "sink" else el.src_pads
    for q in pads:
        if q.name == pad_name:
            return q
    if re.fullmatch(rf"{direction}_\d+", pad_name) is None:
        raise ValueError(
            f"{el.name}: no {direction} pad named {pad_name!r}")
    q = el.request_sink_pad() if direction == "sink" \
        else el.request_src_pad()
    if q.name != pad_name:
        raise ValueError(
            f"{el.name}: pad references must be used in index order "
            f"(requested {pad_name!r}, next available is {q.name!r})")
    return q


def _configure_upstream_from_caps(prev: Optional[Element], caps: Caps,
                                  explicit: set) -> None:
    """gst-launch semantics shortcut: in ``videotestsrc ! video/x-raw,
    format=GRAY8,...`` or ``videoscale ! video/x-raw,width=224,...`` the
    caps filter CONFIGURES the upstream element through negotiation.
    Full upstream negotiation is out of scope for the push scheduler, so
    the parser applies a caps filter's fields directly to the
    directly-preceding element when it exposes a matching configurable
    attribute (format/width/height/framerate/rate/channels) — sources,
    videoconvert (format), videoscale (width/height) alike. Props the
    user set EXPLICITLY stay authoritative: a conflicting caps filter
    then fails negotiation (SSAT negative cases), and the CapsFilter
    still validates whatever the element actually produces."""
    if prev is None or isinstance(prev, tuple):
        return
    for key in ("format", "width", "height", "framerate", "rate",
                "channels"):
        if key not in caps.fields:
            continue
        # gst negotiation propagates through transparent elements
        # (audioconvert/videoconvert/queue): walk upstream until an
        # element exposes the attribute — e.g. `audiotestsrc !
        # audioconvert ! audio/x-raw,rate=8000` configures the SOURCE's
        # rate while audioconvert takes the format. The walk STOPS at
        # media-type boundaries (tensor_converter/decoder) and at other
        # caps filters: an other/tensors field must never clobber an
        # upstream video element's attribute of the same name.
        el, exp = prev, explicit
        for _ in range(6):
            if el.ELEMENT_NAME in ("tensor_converter", "tensor_decoder",
                                   "capsfilter"):
                break
            if hasattr(el, key):
                if key not in exp:
                    old = getattr(el, key)
                    setattr(el, key, caps.fields[key])
                    if old not in (None, caps.fields[key]):
                        # visible trail when a caps filter reconfigures an
                        # upstream element — a same-named attribute with
                        # different semantics would otherwise diverge from
                        # gst negotiation silently
                        from ..core.log import logger

                        logger("parse").info(
                            "caps filter reconfigures %s.%s: %r -> %r",
                            el.name, key, old, caps.fields[key])
                break
            up = el.sink_pads[0].peer if el.sink_pads else None
            if up is None:
                break
            el = up.element
            exp = getattr(el, "_parse_explicit", set())


def _reassemble_caps(kind: str, props: Dict[str, Any]) -> str:
    fields = ",".join(f"{k}={v}" for k, v in props.items())
    return f"{kind},{fields}" if fields else kind


def _split_branches(description: str):
    """Tokenize into branches of segments. Each segment is either
    (element_kind, props) or a back-reference string "name."."""
    # shlex FIRST (punctuation_chars splits bare '!' as its own token) so
    # quoting protects values: model="dir!v2/m" must keep its '!'
    lex = shlex.shlex(description, posix=True, punctuation_chars="!")
    lex.whitespace_split = True
    lex.commenters = ""  # '#' is data (paths, URI fragments), not comments
    tokens: List[str] = []
    for tok in lex:
        if tok and set(tok) == {"!"}:
            # '!!' arrives as one token; expand so the empty-segment
            # check below rejects it
            tokens.extend("!" * len(tok))
        else:
            tokens.append(tok)
    branches: List[List[Any]] = []
    current: List[Any] = []
    seg_tokens: List[str] = []

    def flush_segment() -> None:
        if not seg_tokens:
            return
        # gst caps allow spaces around '=' ("format = RGB"): merge the
        # three-token form (and dangling "k=" / "=v" halves) back into
        # one k=v token before prop parsing. A DANGLING key is "k=" with
        # no earlier '=' — a complete value that merely ENDS in '='
        # (option=YWJjZA==) must not swallow the next token.
        merged: List[str] = []
        for t in seg_tokens:
            if merged and (t == "="
                           or (_dangling_key(merged[-1]) and "=" not in t)
                           or (t.startswith("=") and "="
                               not in merged[-1])):
                merged[-1] += t
            else:
                merged.append(t)
        seg_tokens[:] = merged
        head = seg_tokens[0]
        if len(seg_tokens) == 1 and not any(c in head for c in "=/") and \
                (head.endswith(".") or _PAD_REF_RE.fullmatch(head)):
            current.append(head)
        else:
            props: Dict[str, Any] = {}
            for t in seg_tokens[1:]:
                if "=" not in t:
                    raise ValueError(f"expected prop=value, got {t!r}")
                k, v = t.split("=", 1)
                props[k.replace("-", "_")] = _auto_type(v.strip('"'))
            current.append((head, props))
        seg_tokens.clear()

    for i, tok in enumerate(tokens):
        if tok == "!":
            if not seg_tokens:
                # covers a leading '!' and '! !' (empty segment) alike
                raise ValueError("empty segment before '!' in pipeline")
            if i == len(tokens) - 1:
                raise ValueError("pipeline ends with a dangling '!'")
            flush_segment()
            continue
        # a segment token arriving while another segment is open (no "!"
        # in between) ends the current branch and starts a new one —
        # UNLESS a spaced '=' is pending ("name = queue" is a prop whose
        # value merges in flush_segment, not a new branch)
        eq_pending = bool(seg_tokens) and (seg_tokens[-1] == "="
                                           or _dangling_key(seg_tokens[-1]))
        if seg_tokens and "=" not in tok and not eq_pending \
                and (tok.endswith(".") or _PAD_REF_RE.fullmatch(tok)
                     or _looks_like_element(tok)):
            flush_segment()
            if current:
                branches.append(current)
                current = []
        seg_tokens.append(tok)
    flush_segment()
    if current:
        branches.append(current)
    return branches


#: gst pad reference: ``name.sink_0`` / ``name.src_1`` (the mux/demux
#: SSAT strings link through explicit pads)
_PAD_REF_RE = re.compile(r"[A-Za-z_]\w*\.(sink|src)_\d+")


def _dangling_key(tok: str) -> bool:
    """True for a prop KEY awaiting its value ("name=") — exactly one
    '=' and it is the last character."""
    return tok.endswith("=") and "=" not in tok[:-1]


def _looks_like_element(tok: str) -> bool:
    from .element import element_class

    if "/" in tok or "," in tok or "=" in tok:
        return False
    return element_class(tok) is not None


def caps_to_gst_string(caps: Caps) -> str:
    """Inverse of ``parse_caps_string`` in GStreamer's annotated syntax
    (``media,k=(type)v,...``) — the representation carried on external
    wires (MQTT GstMQTTMessageHdr.gst_caps_str, mqttcommon.h:60)."""
    from fractions import Fraction as _F

    parts = [caps.media_type]
    for k, v in sorted(caps.fields.items()):
        if v is ANY:
            continue
        if k == "dims":
            k = "dimensions"
        elif k == "num":
            k = "num_tensors"
        if isinstance(v, _F):
            parts.append(f"{k}=(fraction){v.numerator}/{v.denominator}")
        elif isinstance(v, bool):
            parts.append(f"{k}=(boolean){'true' if v else 'false'}")
        elif isinstance(v, int):
            parts.append(f"{k}=(int){v}")
        else:
            vs = str(v)
            if "," in vs:
                vs = f'"{vs}"'  # GStreamer quoting for commas
            parts.append(f"{k}=(string){vs}")
    return ",".join(parts)
