"""Pipeline container + scheduler and the structural elements (queue/tee/join).

Scheduling model (GStreamer-equivalent, reduced):
  * each **source** element owns a pacing thread that pushes buffers
    downstream through chain calls (one streaming thread per branch);
  * a **queue** introduces a thread boundary: bounded ring + worker thread,
    producer blocks when full (backpressure) unless leaky;
  * **tee** fans out a branch; **join** merges first-come (reference
    gst/join/gstjoin.c semantics);
  * the **bus** carries errors/EOS out-of-band; ``run()`` drives a pipeline
    to EOS.

Python threads are fine here: per-buffer Python work is bookkeeping; the
compute is XLA dispatch which releases the GIL, and queues between threads
pass jax.Array handles (device-resident) without copies.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

from ..core.buffer import Buffer, now_ns
from ..core.types import Caps
from ..core.log import logger
from ..obs import events as _events
from .element import (Element, FlowReturn, Pad, join_or_warn,
                      register_element, make_element)
from .events import Bus, Event, EventType, Message, MessageType

log = logger("pipeline")

#: process-default scheduler hook (nnstreamer_tpu.sched.install sets /
#: clears this): called as ``hook(pipeline) -> Optional[DeviceEngine]``
#: when a pipeline WITHOUT an explicit ``scheduler=`` starts, so
#: ``nns-launch --sched`` reaches pipelines constructed anywhere.
#: Disabled cost: one global load + None check per Pipeline.start —
#: the same zero-overhead-when-off contract as the CHAOS/PROFILE chain
#: hooks (graph/element.py).
SCHED_PIPELINE_HOOK = None


class SourceElement(Element):
    """Base for sources: owns a thread calling ``create()`` until EOS/stop.

    Subclasses implement ``negotiate() -> Caps`` and
    ``create() -> Optional[Buffer]`` (None = EOS). ``live=True`` paces
    pushes to the buffer duration (camera-like); otherwise pushes as fast
    as downstream accepts (backpressure via queue/chain).
    """

    ELEMENT_NAME = "basesrc"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.live = False
        self.num_buffers = -1  # -1 = unlimited (gst num-buffers prop)
        super().__init__(name, **props)
        if not self.src_pads:
            self.add_src_pad()
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()

    # vmethods ---------------------------------------------------------------
    def negotiate(self) -> Caps:
        raise NotImplementedError

    def create(self) -> Optional[Buffer]:
        raise NotImplementedError

    # lifecycle --------------------------------------------------------------
    def start(self) -> None:
        self._stop_flag.clear()
        self._thread = threading.Thread(target=self._loop, name=f"src:{self.name}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_flag.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            join_or_warn(t, self.name)
        self._thread = None

    def _loop(self) -> None:
        try:
            caps = self.negotiate()
            self.push_event_all(Event(EventType.STREAM_START))
            self.send_caps_all(caps)
        except Exception as e:  # noqa: BLE001
            self.post_error(f"negotiation failed: {e}", exc=e)
            return
        count = 0
        t0 = time.monotonic()
        while not self._stop_flag.is_set():
            if self.num_buffers >= 0 and count >= self.num_buffers:
                break
            try:
                buf = self.create()
            except Exception as e:  # noqa: BLE001
                self.post_error(f"create failed: {e}", exc=e)
                return
            if buf is None:
                break
            if self.live and buf.pts is not None:
                target = t0 + buf.pts / 1e9
                delay = target - time.monotonic()
                if delay > 0:
                    if self._stop_flag.wait(delay):
                        break
            ret = self.push(buf)
            count += 1
            if ret is FlowReturn.ERROR:
                return  # error already on bus
            if ret is FlowReturn.EOS:
                break
        self.push_event_all(Event.eos())


@register_element
class Queue(Element):
    """Thread-decoupling bounded queue with backpressure.

    ``max_size_buffers`` bounds occupancy; producer blocks when full unless
    ``leaky`` ("upstream" drops newest, "downstream" drops oldest) — GStreamer
    queue semantics, which tensor pipelines use for parallel branches.
    """

    ELEMENT_NAME = "queue"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.max_size_buffers = 16
        self.leaky: Optional[str] = None  # None | "upstream" | "downstream"
        super().__init__(name, **props)
        self.add_sink_pad()
        self.add_src_pad()
        self._dq: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._flushing = False

    def start(self) -> None:
        self._flushing = False
        self._worker = threading.Thread(target=self._drain, name=f"q:{self.name}",
                                        daemon=True)
        self._worker.start()

    def stop(self) -> None:
        with self._cv:
            self._flushing = True
            self._cv.notify_all()
        w = self._worker
        if w is not None and w is not threading.current_thread():
            join_or_warn(w, self.name)
        self._worker = None
        self._dq.clear()

    def _enqueue(self, item: Any) -> None:
        # leaky policies apply to buffers only; in-band events (CAPS/EOS)
        # must never be dropped or downstream never negotiates/terminates
        is_event = isinstance(item, Event)
        with self._cv:
            if not is_event:
                def occupancy() -> int:
                    return sum(1 for it in self._dq if isinstance(it, Buffer))

                if self.leaky == "upstream" and occupancy() >= self.max_size_buffers:
                    return  # drop newest
                while occupancy() >= self.max_size_buffers and not self._flushing:
                    if self.leaky == "downstream":
                        self._drop_oldest_buffer()
                        break
                    self._cv.wait(0.1)
            if self._flushing:
                return
            self._dq.append(item)
            self._cv.notify_all()

    def _drop_oldest_buffer(self) -> None:
        for i, it in enumerate(self._dq):
            if isinstance(it, Buffer):
                del self._dq[i]
                return

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        self._enqueue(buf)
        return FlowReturn.OK

    def health_probe(self) -> Dict[str, int]:
        """Occupancy/bound for the health watchdog's queue-dwell rule
        (obs/health.py) — a monitoring sample, unlocked like the
        qdepth gauge."""
        return {"depth": len(self._dq), "bound": int(self.max_size_buffers)}

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        self._enqueue(Event.caps(caps))

    def handle_event(self, pad: Pad, event: Event) -> None:
        self._enqueue(event)

    def _event_entry(self, pad: Pad, event: Event) -> None:
        # EOS must flow through the queue in-order, not bypass it
        if event.type is EventType.EOS:
            self._enqueue(event)
            return
        super()._event_entry(pad, event)

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._dq and not self._flushing:
                    self._cv.wait(0.1)
                if self._flushing:
                    return
                item = self._dq.popleft()
                self._cv.notify_all()
            if isinstance(item, Buffer):
                self.push(item)
            elif isinstance(item, Event):
                if item.type is EventType.EOS:
                    super()._event_entry(self.sink_pad, item)
                elif item.type is EventType.CAPS:
                    self.send_caps_all(item.data["caps"])
                else:
                    self.push_event_all(item)


@register_element
class Tee(Element):
    """1→N fan-out. Buffers are immutable so no copy is made."""

    ELEMENT_NAME = "tee"

    def __init__(self, name: Optional[str] = None, **props: Any):
        super().__init__(name, **props)
        self.add_sink_pad()

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        worst = FlowReturn.OK
        for i in range(len(self.src_pads)):
            ret = self.push(buf, i)
            if ret is FlowReturn.ERROR:
                worst = ret
        return worst


@register_element
class Join(Element):
    """N→1 first-come-wins fan-in (reference gst/join/gstjoin.c): forwards
    buffers from whichever sink pad delivers; caps taken from the first pad
    to negotiate, others must match."""

    ELEMENT_NAME = "join"

    def __init__(self, name: Optional[str] = None, **props: Any):
        super().__init__(name, **props)
        self.add_src_pad()
        self._caps_sent = False

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        with self._lock:
            if not self._caps_sent:
                self._caps_sent = True
                self.send_caps_all(caps)

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        with self._lock:
            return self.push(buf)


class Pipeline:
    """Container + lifecycle manager for an element graph."""

    def __init__(self, name: str = "pipeline", scheduler: Any = None,
                 *, sched_weight: float = 1.0, sched_priority: int = 0,
                 sched_deadline_ms: Optional[float] = None):
        self.name = name
        self.elements: Dict[str, Element] = {}
        self.bus = Bus()
        self._sinks_eos: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self.running = False
        #: fuse transform→filter chains into one XLA program at start
        #: (ops.fusion upstream; ops.epilogue mirrors it downstream)
        self.auto_fuse = True
        self._fused_count = 0
        self._epilogue_count = 0
        #: opt-in multi-tenant dispatch (sched.DeviceEngine): when set,
        #: start() enrolls this pipeline as a tenant — its filters'
        #: invokes coalesce with other tenants' on one dispatch loop.
        #: None (default) keeps the direct per-filter dispatch path.
        #: The sched_* knobs are this tenant's fairness parameters
        #: (DeviceEngine.attach_pipeline reads them).
        self.scheduler = scheduler
        self.sched_weight = sched_weight
        self.sched_priority = sched_priority
        self.sched_deadline_ms = sched_deadline_ms
        self._sched_engine: Any = None

    # -- construction -------------------------------------------------------- #
    def add(self, *elements: Element) -> Union[Element, Sequence[Element]]:
        for el in elements:
            if el.name in self.elements:
                raise ValueError(f"duplicate element name {el.name!r}")
            self.elements[el.name] = el
            el.bus = self.bus
            el.pipeline = self
        return elements[0] if len(elements) == 1 else elements

    def get_by_name(self, name: str) -> Optional["Element"]:
        """Look up an element by its name (gst_bin_get_by_name analog)."""
        return self.elements.get(name)

    def add_new(self, kind: str, name: Optional[str] = None, **props: Any) -> Element:
        el = make_element(kind, element_name=name, **props)
        self.add(el)
        return el

    @staticmethod
    def link(*elements: Element) -> None:
        """Chain-link: a ! b ! c. Picks the first unlinked src/sink pad,
        requesting pads from tee/mux-style elements as needed."""
        for a, b in zip(elements, elements[1:]):
            a.free_src_pad().link(b.free_sink_pad())

    def add_linked(self, *elements: Element) -> Sequence[Element]:
        self.add(*elements)
        self.link(*elements)
        return elements

    # -- lifecycle ------------------------------------------------------------ #
    def start(self) -> None:
        if self.running:
            return
        with self._lock:
            # start() racing a late _sink_eos from the previous run must
            # not lose the wipe (set.clear vs add interleave)
            self._sinks_eos.clear()
        self.bus.clear()
        for el in self.elements.values():
            self._validate_links(el)
            el._quitting = False
            el.prepare()
            el._eos_pads.clear()
            for p in el.sink_pads + el.src_pads:
                p.eos = False
        if self.auto_fuse:
            from ..ops.fusion import fuse_chains

            self._fused_count = fuse_chains(self)
        # live telemetry (obs subsystem): wraps element chains into the
        # process-global registry ONLY when metrics are enabled — when
        # they are not, chains stay the plain class methods and the hot
        # path pays exactly nothing (the no-op fast path tests pin)
        from ..obs.instrument import maybe_instrument_pipeline

        maybe_instrument_pipeline(self)
        # start non-sources first so threads/queues are ready, then sources
        try:
            for el in self.elements.values():
                if not el.is_source:
                    el.start()
                    el.started = True
            # downstream mirror of fuse_chains: runs AFTER non-sources
            # started (decoder instances exist, filter backends are open)
            # and BEFORE sched enrollment (coalesce tokens must be final
            # when the engine starts keying batches)
            if self.auto_fuse:
                from ..ops.epilogue import fuse_epilogues

                self._epilogue_count = fuse_epilogues(self)
            # multi-tenant dispatch opt-in: enroll AFTER non-sources
            # started (filter backends are open) and BEFORE any source
            # thread pushes, so the first buffer already coalesces.
            # Explicit scheduler= wins; otherwise the process-default
            # hook (sched.install / nns-launch --sched) decides.
            sched = self.scheduler
            if sched is None and SCHED_PIPELINE_HOOK is not None:
                sched = SCHED_PIPELINE_HOOK(self)
            if sched is not None:
                sched.attach_pipeline(self)
                self._sched_engine = sched
            for el in self.elements.values():
                if el.is_source:
                    el.start()
                    el.started = True
        except Exception:
            # roll back: elements already started must not leak threads.
            # Sources first (mirroring stop()) and best-effort per element
            # so one failing stop cannot strand the rest.
            for el in sorted(self.elements.values(),
                             key=lambda e: not e.is_source):
                if el.started:
                    try:
                        el.stop()
                    except Exception:  # noqa: BLE001
                        log.exception("rollback stop failed for %s", el.name)
                    el.started = False
            if self._sched_engine is not None:
                self._sched_engine.detach_pipeline(self)
                self._sched_engine = None
            raise
        self.running = True
        # flight recorder (one flag check while off): state transitions
        # bracket the journal a post-mortem dump reads
        _events.record("pipeline.state", f"{self.name} PLAYING",
                       pipeline=self.name)

    def _validate_links(self, el: Element) -> None:
        for p in el.sink_pads + el.src_pads:
            if p.peer is None:
                raise ValueError(f"unlinked pad {p.full_name}")

    def stop(self) -> None:
        if not self.running:
            return
        for el in self.elements.values():
            el.request_stop()  # unblock cross-element waits before joins
        for el in self.elements.values():
            if el.is_source:
                el.stop()
                el.started = False
        for el in self.elements.values():
            if el.started:
                el.stop()
                el.started = False
        if self._sched_engine is not None:
            # after the element joins: chain threads are gone, so the
            # tenant's queue is quiescent — deregistration sheds any
            # stragglers rather than stranding their futures
            self._sched_engine.detach_pipeline(self)
            self._sched_engine = None
        self.running = False
        _events.record("pipeline.state", f"{self.name} stopped",
                       pipeline=self.name)

    def _sink_eos(self, el: Element) -> None:
        with self._lock:
            self._sinks_eos.add(el.name)
            n_sinks = sum(1 for e in self.elements.values() if e.is_sink)
            done = len(self._sinks_eos) >= n_sinks
        if done:
            self.bus.post(Message(MessageType.EOS, self.name))

    def wait_eos(self, timeout: Optional[float] = None) -> bool:
        return self.bus.wait_eos(timeout)

    def run(self, timeout: Optional[float] = None) -> None:
        """Start, wait for EOS (or error), stop. Raises on bus error."""
        self.start()
        try:
            if not self.wait_eos(timeout):
                raise TimeoutError(f"pipeline {self.name!r} did not reach EOS")
            err = self.bus.error
            if err is not None:
                exc = err.data.get("exception")
                raise PipelineError(f"{err.source}: {err.data.get('text')}") from exc
        finally:
            self.stop()

    def __getitem__(self, name: str) -> Element:
        return self.elements[name]

    def __enter__(self) -> "Pipeline":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


class PipelineError(RuntimeError):
    pass
