"""Pipeline graph runtime: elements, pads, events, scheduling, sync."""

from .element import (
    Element,
    FlowReturn,
    Pad,
    PadDirection,
    all_element_names,
    element_class,
    make_element,
    register_element,
)
from .events import Bus, Event, EventType, Message, MessageType
from .pipeline import Join, Pipeline, PipelineError, Queue, SourceElement, Tee
from .sync import CollectPads, SyncPolicy

__all__ = [
    "Element", "FlowReturn", "Pad", "PadDirection", "all_element_names",
    "element_class", "make_element", "register_element",
    "Bus", "Event", "EventType", "Message", "MessageType",
    "Join", "Pipeline", "PipelineError", "Queue", "SourceElement", "Tee",
    "CollectPads", "SyncPolicy",
]
