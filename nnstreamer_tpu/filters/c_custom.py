"""framework=custom — C shared-object filter loader.

Reference: gst/nnstreamer/tensor_filter/tensor_filter_custom.c loading .so
files that implement the custom-filter ABI (tensor_filter_custom.h:46-143).

TWO binary contracts load here, auto-detected by exported symbol:
 * the REFERENCE's ``NNStreamer_custom`` vtable (a .so compiled against
   the reference's own headers runs unmodified — filters/gst_custom_abi.py
   maps the pure-C structs with ctypes);
 * our flat ABI, native/nns_custom.h (simple C symbols; see that header
   for the contract and ``nns-new-filter --kind c`` for a generator).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.types import TensorsInfo
from .base import FilterFramework, FilterProps, register_filter


class _NnsTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p), ("size", ctypes.c_uint64)]


@register_filter
class CCustomFilter(FilterFramework):
    NAME = "custom"
    ALLOCATE_IN_INVOKE = False

    def __init__(self) -> None:
        super().__init__()
        self._lib: Optional[ctypes.CDLL] = None
        self._gst = None  # reference-ABI loader when detected
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        path = props.model_path
        if not path or not os.path.isfile(path):
            raise FileNotFoundError(f"custom filter .so not found: {path}")
        lib = ctypes.CDLL(os.path.abspath(path))
        from .gst_custom_abi import GstCustomSo, detect

        if detect(lib):
            # reference ABI: .so exports NNStreamer_custom (construction
            # errors — e.g. NULL initfunc — surface as themselves)
            self._gst = GstCustomSo(lib, os.path.abspath(path),
                                    props.custom or "")
            self._lib = lib
            self._in_info, self._out_info = self._gst.get_model_info()
            return
        for sym in ("nns_custom_get_input_info", "nns_custom_get_output_info",
                    "nns_custom_invoke"):
            if not hasattr(lib, sym):
                raise ValueError(f"{path}: missing required symbol {sym}")
        lib.nns_custom_get_input_info.restype = ctypes.c_int
        lib.nns_custom_get_input_info.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_char_p, ctypes.c_int]
        lib.nns_custom_get_output_info.restype = ctypes.c_int
        lib.nns_custom_get_output_info.argtypes = lib.nns_custom_get_input_info.argtypes
        lib.nns_custom_invoke.restype = ctypes.c_int
        lib.nns_custom_invoke.argtypes = [
            ctypes.c_int, ctypes.POINTER(_NnsTensor),
            ctypes.c_int, ctypes.POINTER(_NnsTensor)]
        if hasattr(lib, "nns_custom_init"):
            lib.nns_custom_init.restype = ctypes.c_int
            lib.nns_custom_init.argtypes = [ctypes.c_char_p]
            ret = lib.nns_custom_init(props.custom.encode())
            if ret != 0:
                raise RuntimeError(f"{path}: nns_custom_init failed ({ret})")
        self._lib = lib
        self._in_info = self._query_info(lib.nns_custom_get_input_info)
        self._out_info = self._query_info(lib.nns_custom_get_output_info)

    @staticmethod
    def _query_info(fn) -> TensorsInfo:
        cap = 512
        dims = ctypes.create_string_buffer(cap)
        types = ctypes.create_string_buffer(cap)
        if fn(dims, types, cap) != 0:
            raise RuntimeError("custom filter info query failed")
        return TensorsInfo.from_strings(dims.value.decode(), types.value.decode())

    def close(self) -> None:
        if getattr(self, "_gst", None) is not None:
            self._gst.close()
            self._gst = None
        elif self._lib is not None and hasattr(self._lib, "nns_custom_exit"):
            self._lib.nns_custom_exit()
        self._lib = None
        super().close()

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        if getattr(self, "_gst", None) is not None:
            out = self._gst.set_input_info(in_info)
            if out is not None:
                self._in_info, self._out_info = in_info, out
                return out
        return super().set_input_info(in_info)

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        if getattr(self, "_gst", None) is not None:
            outs = self._gst.invoke([m.host() for m in inputs],
                                    self._out_info)
            if outs is None:
                return None  # soft drop (reference ret>0 semantics)
            return [TensorMemory(o) for o in outs]
        n_in = len(inputs)
        in_arrays = [np.ascontiguousarray(m.host()) for m in inputs]
        in_structs = (_NnsTensor * n_in)()
        for i, a in enumerate(in_arrays):
            in_structs[i].data = a.ctypes.data
            in_structs[i].size = a.nbytes
        outs = [np.empty(i.shape, i.dtype.np_dtype) for i in self._out_info]
        out_structs = (_NnsTensor * len(outs))()
        for i, a in enumerate(outs):
            out_structs[i].data = a.ctypes.data
            out_structs[i].size = a.nbytes
        ret = self._lib.nns_custom_invoke(n_in, in_structs, len(outs), out_structs)
        if ret < 0:
            raise RuntimeError(f"custom filter invoke failed ({ret})")
        if ret > 0:
            return None  # soft drop (reference ret>0 semantics)
        return [TensorMemory(a) for a in outs]
