"""NN backend (filter subplugin) API.

Equivalent of ``GstTensorFilterFramework`` v1
(nnstreamer_plugin_api_filter.h:273-495): a vtable of open/close/invoke/
getModelInfo/eventHandler that any backend implements, registered under
``SubpluginType.FILTER``. TPU-first difference: ``invoke`` consumes and
produces :class:`TensorMemory` which may be **device-resident jax.Arrays** —
a backend that runs on TPU never copies through host between pipeline
elements (the reference's GPU backends round-trip through CPU buffers or
managed memory; tensorrt.cc:390).

Also hosts:
 * ``FilterProps`` — parsed element properties handed to ``open``;
 * invoke statistics (GstTensorFilterStatistics, tensor_filter_common.h:80-89);
 * the shared-model table (``shared-tensor-filter-key``,
   tensor_filter_common.c:570-602 nnstreamer_filter_shared_model_*);
 * framework auto-detection from model path
   (gst_tensor_filter_detect_framework, tensor_filter_common.c:1153-1260).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..core.buffer import TensorMemory
from ..core.hw import AcceleratorSpec
from ..core.log import logger
from ..core.registry import SubpluginType, get_subplugin, register_subplugin
from ..core.types import TensorsInfo

log = logger("filter")


@dataclass
class FilterProps:
    """Properties delivered to a backend's open() (GstTensorFilterProperties)."""

    model: Any = None                 # path(s) or in-process object
    custom: str = ""                  # backend-specific option string
    accelerator: AcceleratorSpec = field(default_factory=AcceleratorSpec)
    input_info: Optional[TensorsInfo] = None   # user override / hint
    output_info: Optional[TensorsInfo] = None
    num_threads: int = 0
    is_updatable: bool = False
    #: per-tensor data layouts declared by the inputlayout/outputlayout
    #: props ("none"/"any"/"nhwc"/"nchw" — tensor_filter_common.c:913-940);
    #: empty tuple = unspecified
    input_layout: tuple = ()
    output_layout: tuple = ()

    @property
    def model_path(self) -> Optional[str]:
        if isinstance(self.model, str):
            return self.model
        if isinstance(self.model, (list, tuple)) and self.model \
                and isinstance(self.model[0], str):
            return self.model[0]
        return None

    def custom_dict(self) -> Dict[str, str]:
        """Parse "key=value,key2=value2" custom strings."""
        out: Dict[str, str] = {}
        for part in self.custom.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
            else:
                out[part] = "true"
        return out


class FilterFramework:
    """Backend base class. Subclasses set NAME and implement the vtable."""

    NAME = "base"
    #: backend allocates outputs itself (zero-copy wrap downstream;
    #: reference allocate_in_invoke, tensor_filter.c:308-319)
    ALLOCATE_IN_INVOKE = True
    #: backend works without a model file (e.g. custom-easy callable)
    RUN_WITHOUT_MODEL = False
    #: backend consumes inputlayout/outputlayout=NCHW (permutes data);
    #: declaring NCHW on a backend that would silently ignore it is
    #: rejected at open (tensor_filter element)
    SUPPORTS_LAYOUT = False

    def __init__(self) -> None:
        self.props: Optional[FilterProps] = None

    # -- lifecycle ---------------------------------------------------------- #
    def open(self, props: FilterProps) -> None:
        self.props = props

    def close(self) -> None:
        self.props = None

    # -- model metadata ------------------------------------------------------ #
    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        """(input_info, output_info); either may be None if the model adapts
        to the incoming stream (then set_input_info must resolve it)."""
        raise NotImplementedError

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        """Reconfigure for a given input (setInputDimension); returns the
        resulting output info. Default: reject reconfiguration."""
        raise RuntimeError(f"{self.NAME}: model input is fixed")

    # -- execution ----------------------------------------------------------- #
    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        raise NotImplementedError

    # -- events -------------------------------------------------------------- #
    def reload_model(self, model: Any) -> None:
        """Hot model swap (RELOAD_MODEL, nnstreamer_plugin_api_filter.h:377-383)."""
        raise RuntimeError(f"{self.NAME}: reload not supported")

    def handle_event(self, name: str, data: Dict[str, Any]) -> None:
        """Other custom events; default ignore."""


# --------------------------------------------------------------------------- #
# Registration & lookup
# --------------------------------------------------------------------------- #

def register_filter(cls: type) -> type:
    """Class decorator: register a FilterFramework under its NAME (and
    aliases in cls.ALIASES)."""
    register_subplugin(SubpluginType.FILTER, cls.NAME, cls, replace=True)
    for alias in getattr(cls, "ALIASES", ()):  # e.g. "xla" for "xla-tpu"
        register_subplugin(SubpluginType.FILTER, alias, cls, replace=True)
    return cls


def find_filter(name: str) -> Optional[type]:
    from . import _ensure_builtin_filters

    _ensure_builtin_filters()
    impl = get_subplugin(SubpluginType.FILTER, name)
    return impl


def detect_framework(model: Any) -> Optional[str]:
    """framework=auto: detect from the model object / file extension via the
    config priority table (tensor_filter_common.c:1153,1200,1416)."""
    from ..core.config import get_config

    if model is None:
        return None
    if callable(model) or not isinstance(model, (str, list, tuple)):
        return "xla-tpu"  # in-process jax callables / flax modules
    path = model if isinstance(model, str) else model[0]
    if isinstance(path, str) and path.startswith("zoo://"):
        return "xla-tpu"
    ext = os.path.splitext(str(path))[1].lower()
    for fw in get_config().framework_priority(ext) if ext else []:
        if find_filter(fw) is not None:
            return fw
    return None


# --------------------------------------------------------------------------- #
# Invoke statistics (tensor_filter_common.h:80-89; tensor_filter.c:321-420)
# --------------------------------------------------------------------------- #

class InvokeStats:
    """Rolling invoke latency + throughput, exposed as filter props
    ``latency``/``throughput`` like the reference (µs avg of last N;
    FPS×1000 int)."""

    def __init__(self, window: int = 10):
        self.window = window
        self._latencies_ns: Deque[int] = collections.deque(maxlen=window)
        self.total_invoke_num = 0
        self.total_invoke_latency_ns = 0
        self._first_invoke_t: Optional[float] = None
        self._lock = threading.Lock()

    def record(self, latency_ns: int) -> None:
        with self._lock:
            now = time.monotonic()
            if self._first_invoke_t is None:
                self._first_invoke_t = now
            self._latencies_ns.append(latency_ns)
            self.total_invoke_num += 1
            self.total_invoke_latency_ns += latency_ns

    @property
    def latency_us(self) -> int:
        """Average invoke latency over the window, µs (prop `latency`)."""
        with self._lock:
            if not self._latencies_ns:
                return -1
            return int(sum(self._latencies_ns) / len(self._latencies_ns) / 1000)

    @property
    def throughput(self) -> int:
        """Overall FPS×1000 (prop `throughput`)."""
        with self._lock:
            if self._first_invoke_t is None or self.total_invoke_num < 2:
                return -1
            elapsed = time.monotonic() - self._first_invoke_t
            if elapsed <= 0:
                return -1
            return int(self.total_invoke_num / elapsed * 1000)


# --------------------------------------------------------------------------- #
# Shared model table (shared-tensor-filter-key)
# --------------------------------------------------------------------------- #

_shared_lock = threading.Lock()
_shared_table: Dict[str, FilterFramework] = {}
_shared_refs: Dict[str, int] = {}


def shared_model_get_or_create(key: str, factory) -> FilterFramework:
    with _shared_lock:
        fw = _shared_table.get(key)
        if fw is None:
            fw = factory()
            _shared_table[key] = fw
            _shared_refs[key] = 0
        _shared_refs[key] += 1
        return fw


def shared_model_release(key: str) -> bool:
    """Returns True when the last reference is gone (caller closes fw)."""
    with _shared_lock:
        if key not in _shared_table:
            return False
        _shared_refs[key] -= 1
        if _shared_refs[key] <= 0:
            del _shared_table[key]
            del _shared_refs[key]
            return True
        return False
