"""NN backend subplugins. Importing registers the built-ins."""

from .base import (
    FilterFramework,
    FilterProps,
    InvokeStats,
    detect_framework,
    find_filter,
    register_filter,
)
from .custom import register_custom_easy, unregister_custom_easy

_loaded = False


def _ensure_builtin_filters() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import xla  # noqa: F401
    from . import custom  # noqa: F401
    from . import c_custom  # noqa: F401
    try:
        from . import torch_backend  # noqa: F401
    except ImportError:  # torch genuinely absent
        pass
    from . import tf_backend  # noqa: F401 — tf itself imports at open()


_ensure_builtin_filters()

__all__ = [
    "FilterFramework", "FilterProps", "InvokeStats", "detect_framework",
    "find_filter", "register_filter", "register_custom_easy",
    "unregister_custom_easy",
]
