"""Reference custom-filter .so ABI (``NNStreamer_custom``), ctypes-mapped.

``framework=custom`` loads two ABIs: our flat native/nns_custom.h contract
(filters/c_custom.py) and — this module — the REFERENCE's binary contract
(gst/nnstreamer/include/tensor_filter_custom.h:46-143): the .so exports a
``NNStreamer_custom_class *NNStreamer_custom`` vtable of eight function
pointers operating on the pure-C structs from tensor_typedef.h
(GstTensorMemory / GstTensorInfo / GstTensorsInfo) and
nnstreamer_plugin_api_filter.h:139-164 (GstTensorFilterProperties). All of
those are glib-free by design ("char instead of gchar for non-glib custom
plugins"), so a custom filter compiled against the reference headers loads
here unmodified.

Only the fields custom filters actually consume are populated in the
properties struct (model path, custom_properties, input/output meta);
layout/rank arrays are zeroed (= _NNS_LAYOUT_ANY / unset), matching a
fresh reference properties block before negotiation.
"""

from __future__ import annotations

import ctypes
from ctypes import (
    CFUNCTYPE,
    POINTER,
    Structure,
    c_char_p,
    c_int,
    c_size_t,
    c_uint,
    c_uint32,
    c_void_p,
)
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core.types import TensorDType, TensorInfo, TensorsInfo

#: NNS_TENSOR_RANK_LIMIT / NNS_TENSOR_SIZE_LIMIT (tensor_typedef.h:34-35).
#: RANK must be exactly 4: ``tensor_dim`` is ``uint32_t[4]``, and a wrong
#: array length shifts every subsequent struct offset the compiled .so
#: reads/writes (GstTensorsInfo embeds 16 GstTensorInfo, and the
#: properties block embeds two GstTensorsInfo).
RANK_LIMIT = 4
SIZE_LIMIT = 16

#: reference ``tensor_type`` enum order (tensor_typedef.h:153-167)
_DTYPES = [TensorDType.INT32, TensorDType.UINT32, TensorDType.INT16,
           TensorDType.UINT16, TensorDType.INT8, TensorDType.UINT8,
           TensorDType.FLOAT64, TensorDType.FLOAT32,
           TensorDType.INT64, TensorDType.UINT64]
_DTYPE_TO_ENUM = {d: i for i, d in enumerate(_DTYPES)}


class GstTensorMemory(Structure):
    _fields_ = [("data", c_void_p), ("size", c_size_t)]


class GstTensorInfo(Structure):
    _fields_ = [("name", c_char_p),
                ("type", c_int),
                ("dimension", c_uint32 * RANK_LIMIT)]


class GstTensorsInfo(Structure):
    _fields_ = [("num_tensors", c_uint),
                ("info", GstTensorInfo * SIZE_LIMIT)]


class GstTensorFilterProperties(Structure):
    # nnstreamer_plugin_api_filter.h:139-164, field for field
    _fields_ = [
        ("fwname", c_char_p),
        ("fw_opened", c_int),
        ("model_files", POINTER(c_char_p)),
        ("num_models", c_int),
        ("input_configured", c_int),
        ("input_meta", GstTensorsInfo),
        ("input_layout", c_int * SIZE_LIMIT),
        ("input_ranks", c_uint * SIZE_LIMIT),
        ("output_configured", c_int),
        ("output_meta", GstTensorsInfo),
        ("output_layout", c_int * SIZE_LIMIT),
        ("output_ranks", c_uint * SIZE_LIMIT),
        ("custom_properties", c_char_p),
        ("hw_list", c_void_p),
        ("num_hw", c_int),
        ("accl_str", c_char_p),
        ("shared_tensor_filter_key", c_char_p),
        ("latency", c_int),
        ("throughput", c_int),
    ]


_InitFn = CFUNCTYPE(c_void_p, POINTER(GstTensorFilterProperties))
_ExitFn = CFUNCTYPE(None, c_void_p, POINTER(GstTensorFilterProperties))
_GetDimFn = CFUNCTYPE(c_int, c_void_p, POINTER(GstTensorFilterProperties),
                      POINTER(GstTensorsInfo))
_SetDimFn = CFUNCTYPE(c_int, c_void_p, POINTER(GstTensorFilterProperties),
                      POINTER(GstTensorsInfo), POINTER(GstTensorsInfo))
_InvokeFn = CFUNCTYPE(c_int, c_void_p, POINTER(GstTensorFilterProperties),
                      POINTER(GstTensorMemory), POINTER(GstTensorMemory))
_DestroyFn = CFUNCTYPE(None, c_void_p)


class NNStreamerCustomClass(Structure):
    # struct _NNStreamer_custom_class (tensor_filter_custom.h:126-137)
    _fields_ = [
        ("initfunc", _InitFn),
        ("exitfunc", _ExitFn),
        ("getInputDim", _GetDimFn),
        ("getOutputDim", _GetDimFn),
        ("setInputDim", _SetDimFn),
        ("invoke", _InvokeFn),
        ("allocate_invoke", _InvokeFn),
        ("destroy_notify", _DestroyFn),
    ]


def struct_to_info(meta: GstTensorsInfo) -> Optional[TensorsInfo]:
    if meta.num_tensors == 0:
        return None
    infos = []
    for i in range(meta.num_tensors):
        ti = meta.info[i]
        dims = []
        for d in ti.dimension:
            if d == 0:
                break
            dims.append(int(d))
        while len(dims) > 1 and dims[-1] == 1:
            dims.pop()
        infos.append(TensorInfo(tuple(dims), _DTYPES[ti.type]))
    return TensorsInfo(tuple(infos))


def info_to_struct(info: TensorsInfo, meta: GstTensorsInfo) -> None:
    meta.num_tensors = len(info)
    for i, t in enumerate(info):
        if t.dtype not in _DTYPE_TO_ENUM:
            raise ValueError(
                f"dtype {t.dtype} has no reference tensor_type enum value "
                "— the custom .so ABI cannot carry bf16/f16 streams")
        meta.info[i].name = None
        meta.info[i].type = _DTYPE_TO_ENUM[t.dtype]
        dims = list(t.dims) + [1] * (RANK_LIMIT - len(t.dims))
        for j in range(RANK_LIMIT):
            meta.info[i].dimension[j] = dims[j]


def detect(lib: ctypes.CDLL) -> bool:
    """True iff the .so exports the reference's NNStreamer_custom symbol
    (detection only — a present-but-invalid vtable must surface ITS error
    from the constructor, not fall through to the flat-ABI probe)."""
    try:
        POINTER(NNStreamerCustomClass).in_dll(lib, "NNStreamer_custom")
        return True
    except ValueError:
        return False


class GstCustomSo:
    """A loaded reference-ABI custom filter (one instance per element)."""

    def __init__(self, lib: ctypes.CDLL, path: str, custom: str):
        self._cls = POINTER(NNStreamerCustomClass).in_dll(
            lib, "NNStreamer_custom").contents
        if not self._cls.initfunc:
            # the reference rejects this at open too
            # (tensor_filter_custom.c:114 "requires a valid 'initfunc'")
            raise RuntimeError(
                f"{path}: NNStreamer_custom.initfunc is NULL")
        if bool(self._cls.invoke) == bool(self._cls.allocate_invoke):
            # exactly one of invoke/allocate_invoke must be set
            # (tensor_filter_custom.c custom_open); neither would call a
            # NULL pointer at the first frame, both is ambiguous
            raise RuntimeError(
                f"{path}: NNStreamer_custom must define exactly one of "
                "invoke/allocate_invoke "
                f"(invoke={bool(self._cls.invoke)}, "
                f"allocate_invoke={bool(self._cls.allocate_invoke)})")
        # keep byte buffers alive for the struct's borrowed pointers
        self._path_b = path.encode()
        self._custom_b = custom.encode() if custom else None
        self._models = (c_char_p * 1)(self._path_b)
        self._prop = GstTensorFilterProperties()
        self._prop.fwname = b"custom"
        self._prop.fw_opened = 1
        self._prop.model_files = self._models
        self._prop.num_models = 1
        self._prop.custom_properties = self._custom_b
        self._priv = self._cls.initfunc(ctypes.byref(self._prop))

    # -- model info --------------------------------------------------------- #
    def get_model_info(self) -> Tuple[Optional[TensorsInfo],
                                      Optional[TensorsInfo]]:
        ii = oi = None
        if self._cls.getInputDim:
            meta = GstTensorsInfo()
            if self._cls.getInputDim(self._priv, ctypes.byref(self._prop),
                                     ctypes.byref(meta)) == 0:
                ii = struct_to_info(meta)
        if self._cls.getOutputDim:
            meta = GstTensorsInfo()
            if self._cls.getOutputDim(self._priv, ctypes.byref(self._prop),
                                      ctypes.byref(meta)) == 0:
                oi = struct_to_info(meta)
        if ii is not None:
            info_to_struct(ii, self._prop.input_meta)
            self._prop.input_configured = 1
        if oi is not None:
            info_to_struct(oi, self._prop.output_meta)
            self._prop.output_configured = 1
        return ii, oi

    def set_input_info(self, in_info: TensorsInfo) -> Optional[TensorsInfo]:
        if not self._cls.setInputDim:
            return None
        cin, cout = GstTensorsInfo(), GstTensorsInfo()
        info_to_struct(in_info, cin)
        ret = self._cls.setInputDim(self._priv, ctypes.byref(self._prop),
                                    ctypes.byref(cin), ctypes.byref(cout))
        if ret != 0:
            raise ValueError(f"custom .so setInputDim failed ({ret})")
        out = struct_to_info(cout)
        info_to_struct(in_info, self._prop.input_meta)
        self._prop.input_configured = 1
        if out is not None:
            info_to_struct(out, self._prop.output_meta)
            self._prop.output_configured = 1
        return out

    # -- execution ---------------------------------------------------------- #
    def invoke(self, arrays: Sequence[np.ndarray],
               out_info: TensorsInfo) -> List[np.ndarray]:
        n_in, n_out = len(arrays), len(out_info)
        c_in = (GstTensorMemory * max(n_in, 1))()
        holders = []
        for i, a in enumerate(arrays):
            a = np.ascontiguousarray(a)
            holders.append(a)
            c_in[i].data = a.ctypes.data_as(c_void_p)
            c_in[i].size = a.nbytes
        c_out = (GstTensorMemory * max(n_out, 1))()
        outs: List[np.ndarray] = []
        use_alloc = bool(self._cls.allocate_invoke) and \
            not bool(self._cls.invoke)
        if not use_alloc:
            for i, t in enumerate(out_info):
                o = np.empty(t.shape, t.dtype.np_dtype)
                outs.append(o)
                c_out[i].data = o.ctypes.data_as(c_void_p)
                c_out[i].size = o.nbytes
            ret = self._cls.invoke(self._priv, ctypes.byref(self._prop),
                                   c_in, c_out)
            if ret > 0:
                return None  # soft drop (tensor_filter.c:702-705)
            if ret < 0:
                raise RuntimeError(f"custom .so invoke failed ({ret})")
            return outs
        # allocate_invoke: the plugin allocates; copy out + destroy_notify
        ret = self._cls.allocate_invoke(self._priv, ctypes.byref(self._prop),
                                        c_in, c_out)
        if ret > 0:
            return None  # soft drop
        if ret < 0:
            raise RuntimeError(f"custom .so allocate_invoke failed ({ret})")
        for i, t in enumerate(out_info):
            raw = ctypes.string_at(c_out[i].data, c_out[i].size)
            outs.append(np.frombuffer(raw, t.dtype.np_dtype)
                        .reshape(t.shape).copy())
            if self._cls.destroy_notify:
                self._cls.destroy_notify(c_out[i].data)
        return outs

    def close(self) -> None:
        if self._cls.exitfunc:
            self._cls.exitfunc(self._priv, ctypes.byref(self._prop))
