"""The ``xla-tpu`` backend — first-class NN execution over JAX/XLA.

This is the TPU-native replacement for the reference's device backends
(tensor_filter_tensorrt.cc — the GPU path; tensor_filter_edgetpu.cc — the
NPU path): one backend that compiles models with XLA and keeps all streaming
I/O device-resident in HBM.

Model forms accepted by ``model=``:
  * ``zoo://<name>?opt=val`` — built-in model zoo (models/zoo.py);
  * a Python file path — must export ``make_model(options) -> ModelBundle``
    (or a dict with apply/params/in_info/out_info);
  * an in-process callable ``fn(*arrays)`` or ``(fn, params)`` tuple or
    ModelBundle — pipelines embedded in apps skip serialization entirely;
  * a flax ``nn.Module`` plus ``custom="init=<H,W,C>"`` to self-initialize.

Design notes (TPU-first):
  * inputs are moved to device once (``TensorMemory.device()``); outputs stay
    device-resident — ALLOCATE_IN_INVOKE zero-copy wrap downstream;
  * invoke is **async**: XLA dispatch returns immediately, the pipeline
    blocks only where a host boundary demands it (sink/decoder) — this is
    what lets a streaming pipeline overlap host scheduling with TPU compute;
    set ``custom="sync=true"`` for synchronous per-invoke latency accounting;
  * optional ``custom="donate=true"`` donates input buffers (in-place reuse
    of HBM when shapes/dtypes match);
  * precision: ``custom="precision=bf16"`` casts float inputs to bfloat16 at
    the XLA boundary (MXU-preferred; int inputs untouched);
  * dynamic-count streams (SURVEY §7 hard part b — e.g. tensor_crop regions):
    ``custom="bucket=8"`` stacks a frame's N same-shape tensors into one
    batch, zero-pads N up to the next multiple of 8 so XLA sees a small
    closed set of static shapes (one compile per bucket, cached), invokes
    once, and emits the first N rows as a single stacked result; add
    ``resize=H:W`` to conform variable-size image regions on device first.
"""

from __future__ import annotations

import importlib.util
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.log import logger
from ..core.types import TensorInfo, TensorsInfo
from .. import tune as _tune
from ..models.zoo import ModelBundle, get_model
from ..obs import profile as _profile
from .base import FilterFramework, FilterProps, register_filter

log = logger("xla")


#: custom= keys consumed by the filter itself, not by model factories;
#: stripped before model resolution so identical model specs memoize to one
#: bundle (and thus one compile) regardless of filter-level settings
_FILTER_ONLY_OPTS = frozenset(
    {"sync", "precision", "donate", "bucket", "bucket_max", "resize",
     "arch", "quant"})


def _model_options(options: Dict[str, str]) -> Dict[str, str]:
    return {k: v for k, v in options.items()
            if k not in _FILTER_ONLY_OPTS and not k.startswith("arch_")}


def resolve_model(model: Any, options: Optional[Dict[str, str]] = None) -> ModelBundle:
    """Normalize any accepted model form into a ModelBundle."""
    raw_options = options or {}
    options = _model_options(raw_options)
    if isinstance(model, ModelBundle):
        return model
    if isinstance(model, (list, tuple)) and len(model) == 2 and callable(model[0]):
        fn, params = model
        return ModelBundle(getattr(fn, "__name__", "model"), fn, params=params)
    if callable(model) and not isinstance(model, type):
        # flax module instance?
        try:
            import flax.linen as fnn

            if isinstance(model, fnn.Module):
                return _bundle_from_flax(model, options)
        except ImportError:
            pass
        return ModelBundle(getattr(model, "__name__", "model"), model)
    if isinstance(model, str):
        from ..models import deploy

        if model.startswith("zoo://") or not os.path.sep in model and not os.path.exists(model) \
                and not model.endswith((".py", ".tflite")) \
                and not deploy.is_deployable_path(model):
            return get_model(model, **options)  # options pre-stripped
        if model.endswith(".py"):
            return _bundle_from_pyfile(model, options)
        if model.lower().endswith(".tflite"):
            from ..models.tflite_import import load_tflite

            return load_tflite(model)
        if model.lower().endswith(deploy.EXPORT_EXTS):
            return deploy.load_exported(model)
        if model.lower().endswith(deploy.CKPT_EXTS) or os.path.isdir(model):
            arch = raw_options.get("arch")
            if not arch:
                raise ValueError(
                    f"checkpoint model {model!r} needs custom=\"arch=...\" "
                    "(a zoo:// spec or make_model .py) to restore into")
            arch_opts = {k[5:]: v for k, v in raw_options.items()
                         if k.startswith("arch_")}
            return deploy.load_checkpointed(model, arch, **arch_opts)
        raise ValueError(f"xla-tpu: unsupported model file {model!r} "
                         "(use zoo://, a .jaxexport artifact, checkpoint "
                         "params + custom=\"arch=...\", a .py exporting "
                         "make_model, or an in-process callable)")
    raise ValueError(f"xla-tpu: cannot interpret model {model!r}")


def _bundle_from_flax(module: Any, options: Dict[str, str]) -> ModelBundle:
    import jax
    import jax.numpy as jnp

    init = options.get("init")
    if not init:
        raise ValueError("flax module models need custom=\"init=H,W,C[,B]\" "
                         "(input shape) to self-initialize")
    shape = tuple(int(x) for x in init.split(";" if ";" in init else ","))
    if len(shape) == 3:
        shape = (1,) + shape
    dummy = jnp.zeros(shape, jnp.float32)
    variables = module.init(jax.random.PRNGKey(int(options.get("seed", 0))), dummy)
    return ModelBundle(type(module).__name__,
                       lambda p, x: module.apply(p, x), params=variables)


def _bundle_from_pyfile(path: str, options: Dict[str, str]) -> ModelBundle:
    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    spec = importlib.util.spec_from_file_location(
        f"nns_tpu_model_{os.path.basename(path).rstrip('.py')}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "make_model"):
        raise ValueError(f"{path}: must export make_model(**options)")
    bundle = mod.make_model(**options)
    if isinstance(bundle, dict):
        bundle = ModelBundle(
            bundle.get("name", os.path.basename(path)),
            bundle["apply"], params=bundle.get("params"),
            in_info=_coerce_info(bundle.get("in_info")),
            out_info=_coerce_info(bundle.get("out_info")))
    return bundle


def _as_tuple(out: Any) -> Tuple[Any, ...]:
    return tuple(out) if isinstance(out, (tuple, list)) else (out,)


def _active_layouts(layouts: Optional[Sequence[str]]) -> Tuple[str, ...]:
    """Layout tuple → itself if any entry permutes (nchw), else ()."""
    layouts = tuple(layouts or ())
    return layouts if any(v == "nchw" for v in layouts) else ()


def _layout_infos(infos: Optional[TensorsInfo],
                  layouts: Sequence[str]) -> Optional[TensorsInfo]:
    """Model-layout (NHWC) TensorsInfo → stream-layout: tensors declared
    NCHW report channel-first dims so caps negotiation matches the wire."""
    if infos is None or not layouts:
        return infos
    out = []
    for i, t in enumerate(infos):
        shape = t.shape
        if i < len(layouts) and layouts[i] == "nchw" and len(shape) == 4:
            n, h, w, c = shape
            out.append(TensorInfo.from_shape((n, c, h, w), t.dtype.np_dtype,
                                             t.name))
        else:
            out.append(t)
    return TensorsInfo(tuple(out))


def _coerce_info(v: Any) -> Optional[TensorsInfo]:
    if v is None or isinstance(v, TensorsInfo):
        return v
    if isinstance(v, (tuple, list)) and len(v) == 2:
        return TensorsInfo.from_strings(v[0], v[1])
    raise ValueError(f"bad tensor info spec {v!r}")


@register_filter
class XLAFilter(FilterFramework):
    """framework=xla-tpu (aliases: xla, jax)."""

    NAME = "xla-tpu"
    #: "tensorflow-lite"/"tensorflow2-lite"/"tensorflow1-lite" are accepted
    #: so reference pipeline strings (framework=tensorflow-lite
    #: model=foo.tflite) run unmodified — the .tflite flatbuffer is imported
    #: and compiled by XLA (models/tflite_import.py) instead of the TFLite
    #: Interpreter (tensor_filter_tensorflow_lite.cc:154)
    ALIASES = ("xla", "jax", "tensorflow-lite", "tensorflow2-lite",
               "tensorflow1-lite", "tflite")
    ALLOCATE_IN_INVOKE = True
    SUPPORTS_LAYOUT = True  # NCHW permutes fuse into the XLA program

    def __init__(self) -> None:
        super().__init__()
        self._bundle: Optional[ModelBundle] = None
        self._jitted: Optional[Callable] = None
        self._device = None
        self._sync = False
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------- #
    def open(self, props: FilterProps) -> None:
        super().open(props)
        opts = props.custom_dict()
        self._bundle = self._maybe_quantize(
            resolve_model(props.model, opts), opts)
        self._refresh_device()
        self._sync = opts.get("sync", "false").lower() in ("1", "true", "yes")
        self._precision = opts.get("precision", "")
        self._donate = opts.get("donate", "false").lower() in ("1", "true", "yes")
        self._bucket = int(opts.get("bucket", "0") or 0)
        # bounded bucket ladder: padded sizes are bucket, 2*bucket, ...
        # up to bucket_max (default 8*bucket). A frame with more tensors
        # than the cap is chunked into cap-sized invokes instead of
        # compiling an ever-larger shape (see _invoke_bucketed).
        bmax = int(opts.get("bucket_max", "0") or 0)
        self._bucket_max = max(bmax, self._bucket) if bmax > 0 \
            else self._bucket * 8
        # inputlayout/outputlayout=NCHW: the stream is channel-first while
        # XLA/zoo models are channel-last — the permutes compile INTO the
        # XLA program (free to fuse, never a host-side copy). Normalized
        # to () unless something actually permutes, so none/any/nhwc
        # declarations never cost the layout staging path.
        self._in_layout = _active_layouts(props.input_layout)
        self._out_layout = _active_layouts(props.output_layout)
        resize = opts.get("resize", "")
        if resize:
            parts = tuple(int(v) for v in resize.split(":"))
            if len(parts) != 2:
                raise ValueError(f"xla-tpu: resize wants H:W, got {resize!r}")
            self._resize = parts
        else:
            self._resize = None
        self.flexible_output = self._bucket > 0
        self._build_jit()
        self._in_info = props.input_info or _layout_infos(
            self._bundle.in_info, self._in_layout)
        self._out_info = props.output_info or _layout_infos(
            self._bundle.out_info, self._out_layout)
        if self._in_info is not None and self._out_info is None:
            self._out_info = self._infer_out_info(self._in_info)
        # cross-filter coalesce anchor (sched.DeviceEngine): two filter
        # instances sharing one resolved bundle (the zoo memoizes equal
        # specs) and identical result-affecting config compute the same
        # function, so the scheduler may batch their work together
        self.coalesce_token = (
            "xla", id(self._bundle), str(self._device), self._precision,
            self._donate, self._bucket, self._bucket_max, self._in_layout,
            self._out_layout, self._resize)
        log.info("xla-tpu opened model=%s device=%s sync=%s",
                 self._bundle.name, self._device, self._sync)

    @staticmethod
    def _maybe_quantize(bundle: ModelBundle, opts: Dict[str, str]) -> ModelBundle:
        """Apply custom="quant=w8" (no-op otherwise). The quantized bundle
        memoizes on the base bundle so filters sharing one resolved spec
        also share one quantization pass and one jit cache/compile."""
        quant = opts.get("quant", "")
        if not quant:
            return bundle
        if quant not in ("w8", "int8", "w8a8"):
            raise ValueError(f"xla-tpu: unknown quant mode {quant!r} "
                             "(supported: w8, int8, w8a8)")
        key = "_w8a8_bundle" if quant == "w8a8" else "_w8_bundle"
        cached = bundle.metadata.get(key)
        if cached is None:
            from ..models.quantize import (quantize_bundle,
                                           quantize_bundle_w8a8)

            cached = (quantize_bundle_w8a8(bundle) if quant == "w8a8"
                      else quantize_bundle(bundle))
            bundle.metadata[key] = cached
        return cached

    def _refresh_device(self) -> None:
        """Input placement target: mesh-sharded bundles
        (parallel.sharded_bundle) carry the input sharding inputs must be
        placed with — jax.device_put accepts a Sharding wherever a Device
        goes, so it simply replaces the single-device target. Re-derived
        on open AND reload (a hot swap to/from a sharded bundle must not
        leave a stale placement)."""
        sharding = self._bundle.metadata.get("input_sharding")
        self._device = sharding if sharding is not None \
            else self.props.accelerator.pick_device()

    def set_fused_preprocess(self, pre, token: Optional[str] = None) -> None:
        """Install a jax-traceable per-tensor preprocessing stage compiled
        into the same XLA program (ops.fusion pass)."""
        self._fused_pre = pre
        self._extend_coalesce_token("pre", token)
        self._build_jit()

    def set_fused_epilogue(self, post, token: Optional[str] = None) -> None:
        """Install a jax-traceable post-processing stage compiled into the
        same XLA program (ops.epilogue pass): applied to the output tuple
        after the stream-layout restore, so a filter→transform/decoder
        tail runs as ONE dispatch per frame. Caps inference still reports
        the model's own (unreduced) outputs — downstream fused elements
        negotiate the unreduced stream and forward/consume the fused
        result (see ``_infer_out_info``)."""
        self._fused_post = post
        self._epilogue_label = (f"{self._bundle.name}+post[{token}]"
                                if self._bundle is not None and token
                                else None)
        self._extend_coalesce_token("post", token)
        self._build_jit()

    def _extend_coalesce_token(self, kind: str, token: Optional[str]) -> None:
        """Two filters sharing one bundle but fused with DIFFERENT chains
        compute different functions — the sched engine must not coalesce
        them. Structural signatures (not ``id()``) extend the token, so
        identical chains still batch together."""
        if getattr(self, "coalesce_token", None) is not None:
            self.coalesce_token = self.coalesce_token + ((kind, token),)

    def _build_jit(self) -> None:
        """Compile (or reuse) the bundle's XLA program. The jit cache
        lives ON the bundle (metadata) so filters over the same resolved
        model — e.g. a latency and a throughput pipeline over one
        memoized zoo spec — share one compile, and the cache dies with
        the bundle (reload_model swaps bundles; nothing pins old params
        or executables)."""
        import jax

        fn = self._bundle.fn()
        precision = self._precision
        pre = getattr(self, "_fused_pre", None)
        post = getattr(self, "_fused_post", None)
        in_layout = getattr(self, "_in_layout", ())
        out_layout = getattr(self, "_out_layout", ())

        def to_model_layout(i, x):
            # stream NCHW -> model NHWC (rank-4 only; others pass through,
            # matching the reference's "layout of the data" scope)
            if i < len(in_layout) and in_layout[i] == "nchw" and x.ndim == 4:
                import jax.numpy as jnp

                return jnp.transpose(x, (0, 2, 3, 1))
            return x

        def to_stream_layout(j, y):
            if j < len(out_layout) and out_layout[j] == "nchw" \
                    and getattr(y, "ndim", 0) == 4:
                import jax.numpy as jnp

                return jnp.transpose(y, (0, 3, 1, 2))
            return y
        if self._bundle.metadata.get("jit") is False:
            # bundle fn is already a compiled/pjit program (sharded
            # serving): an outer jit would re-stage it against the wrong
            # device assignment. Fused preprocess + precision cast still
            # apply — as their own (sharding-preserving) jitted stage.
            if self._donate:
                log.warning("donate=true ignored for pre-compiled (jit "
                            "False) bundle %s", self._bundle.name)
            if pre is not None or precision in ("bf16", "bfloat16") \
                    or in_layout or out_layout:
                def stage(i, x):
                    # fused preprocess FIRST: inputlayout describes the
                    # stream entering the filter, i.e. the fused
                    # transform's OUTPUT — fusion hands us the raw
                    # upstream data, so the transform must run before
                    # the layout permute
                    if pre is not None:
                        x = pre(x)
                    x = to_model_layout(i, x)
                    if precision in ("bf16", "bfloat16"):
                        import jax.numpy as jnp

                        if np.issubdtype(np.dtype(str(x.dtype)),
                                         np.floating):
                            x = x.astype(jnp.bfloat16)
                    return x

                stage_jit = jax.jit(stage, static_argnums=0)
                post_jit = jax.jit(to_stream_layout, static_argnums=0) \
                    if out_layout else None
                self._jitted = lambda *xs: tuple(
                    post_jit(j, y) if post_jit is not None else y
                    for j, y in enumerate(_as_tuple(
                        fn(*(stage_jit(i, x) for i, x in enumerate(xs))))))
            else:
                self._jitted = lambda *xs: _as_tuple(fn(*xs))
            self._infer_fn = self._jitted
            if post is not None:
                # fused epilogue as its own (sharding-preserving) jitted
                # stage, mirroring the preprocess staging above
                base = self._jitted
                epi = jax.jit(lambda *ys: tuple(post(ys)))
                self._jitted = lambda *xs: epi(*base(*xs))
            return
        # fused-preprocess/epilogue programs are per-pipeline objects:
        # caching them on a (memoized, process-lifetime) bundle would leak
        # one compiled executable per pipeline and never actually share
        cache = None if pre is not None or post is not None \
            else self._bundle.metadata.setdefault("_jit_cache", {})
        cache_key = (precision, self._donate, in_layout, out_layout)
        donate_key = (precision, True, in_layout, out_layout)
        if cache is not None:
            hit = cache.get(cache_key)
            if hit is not None:
                if _profile.DISPATCH_HOOK is not None:
                    _profile.DISPATCH_HOOK.on_jit_cache("bundle", True)
                self._jitted = hit
                self._infer_fn = hit
                self._jitted_donate = cache.get(donate_key, hit)
                return

        def wrapped_base(*xs):
            # fused preprocess BEFORE the layout permute (inputlayout
            # describes the fused transform's output stream — see stage())
            if pre is not None:
                xs = tuple(pre(x) for x in xs)
            xs = tuple(to_model_layout(i, x) for i, x in enumerate(xs))
            if precision in ("bf16", "bfloat16"):
                import jax.numpy as jnp

                xs = tuple(x.astype(jnp.bfloat16)
                           if np.issubdtype(np.dtype(str(x.dtype)), np.floating) else x
                           for x in xs)
            return tuple(to_stream_layout(j, y)
                         for j, y in enumerate(_as_tuple(fn(*xs))))

        def wrapped(*xs):
            # fused epilogue AFTER the stream-layout restore: the chain it
            # replaces consumed the filter's wire outputs
            ys = wrapped_base(*xs)
            return tuple(post(ys)) if post is not None else ys

        kw: Dict[str, Any] = {}
        if self._donate:
            kw["donate_argnums"] = tuple(range(8))
        self._jitted = jax.jit(wrapped, **kw)
        # donating twin for the coalesced path: sched's concatenated
        # batch buffer is freshly allocated and exclusively owned, so
        # it can be donated even when the filter's OWN inputs (the
        # user's buffers) must stay intact. Same trace, donate=True key
        # in the shared bundle cache — at most one extra executable.
        if self._donate:
            self._jitted_donate = self._jitted
        else:
            dkw = dict(kw)
            dkw["donate_argnums"] = tuple(range(8))
            self._jitted_donate = jax.jit(wrapped, **dkw)
        # caps inference must see the model's own (unreduced) outputs —
        # the fused epilogue's reduce is invisible to negotiation
        self._infer_fn = jax.jit(wrapped_base) if post is not None \
            else self._jitted
        if cache is not None:
            cache[cache_key] = self._jitted
            cache[donate_key] = self._jitted_donate
            if _profile.DISPATCH_HOOK is not None:
                _profile.DISPATCH_HOOK.on_jit_cache("bundle", False)

    def close(self) -> None:
        self._jitted = None
        self._bundle = None
        super().close()

    # -- model metadata ------------------------------------------------------ #
    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        self._in_info = in_info
        self._out_info = self._infer_out_info(in_info)
        return self._out_info

    def _infer_out_info(self, in_info: TensorsInfo) -> TensorsInfo:
        """Shape-infer outputs via jax.eval_shape (no FLOPs, no transfer)."""
        import jax

        specs = [jax.ShapeDtypeStruct(i.shape, i.dtype.np_dtype) for i in in_info]
        infer = getattr(self, "_infer_fn", None) or self._jitted
        out = jax.eval_shape(infer, *specs)
        infos = tuple(TensorInfo.from_shape(o.shape if o.shape else (1,), o.dtype)
                      for o in out)
        return TensorsInfo(infos)

    # -- execution ----------------------------------------------------------- #
    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        if self._bucket > 0:
            return self._invoke_bucketed(inputs)
        # mesh-sharded bundles require the batch divisible by the mesh's
        # data axis (parallel.sharded_bundle sets batch_multiple=dp); an
        # uneven final batch is zero-padded to the next multiple and the
        # outputs trimmed back — each distinct padded size compiles once
        # (shape-keyed jit cache), and padded sizes are bounded by dp
        mult = int(self._bundle.metadata.get("batch_multiple", 0) or 0) \
            if self._bundle is not None and hasattr(self._bundle, "metadata") \
            else 0
        orig_batch = None
        if mult > 1 and inputs:
            shape0 = inputs[0].shape  # no D2H: metadata only
            if shape0 and shape0[0] % mult:
                import jax

                orig_batch = int(shape0[0])
                pad = mult - orig_batch % mult
                arrays = []
                for m in inputs:
                    h = m.host()
                    padded = np.concatenate(
                        [h, np.zeros((pad,) + h.shape[1:], h.dtype)])
                    arrays.append(jax.device_put(padded, self._device))
        if orig_batch is None:
            arrays = [m.device(self._device) for m in inputs]
        with self._lock:
            # profiled dispatch: one module load + None check when off
            prof = _profile.DISPATCH_HOOK
            if prof is not None:
                outs = prof.dispatch(self, arrays)
            else:
                outs = self._jitted(*arrays)
        if orig_batch is not None:
            # sharded_bundle's out_shardings put every output's leading
            # axis over the data mesh axis, so outputs are batch-led by
            # contract — but an auxiliary output whose fixed dim0 happens
            # to divide the mesh would shard without error, so the trim is
            # still gated on the leading dim matching the padded batch
            outs = tuple(
                o[:orig_batch]
                if getattr(o, "ndim", 0) and o.shape[0] == orig_batch + pad
                else o
                for o in outs)
        if self._sync:
            for o in outs:
                o.block_until_ready()
        return [TensorMemory(o) for o in outs]

    def _invoke_bucketed(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        """N tensors → one padded-batch invoke → one (N, ...) result per
        model output. jax.jit's shape-keyed cache makes each bucket size
        compile exactly once; zero rows are masked off by slicing.

        The ladder is BOUNDED: padded sizes stop at ``bucket_max``
        (default 8*bucket). A frame with more tensors than the cap used
        to silently compile a fresh, ever-larger shape; now it is
        chunked into cap-sized invokes whose stacked outputs are
        concatenated, and a ``sched.bucket_miss`` event records the
        overflow. Hit/pad-waste counters ride ``nnstpu_sched_bucket_*``
        (sched/telemetry.py) so pad waste is observable."""
        import jax
        import jax.numpy as jnp

        from ..sched import telemetry as _sched_tel

        n = len(inputs)
        if n == 0:
            return []
        cap = self._bucket_max
        if n > cap:
            _sched_tel.record_bucket_miss(
                n, cap, label=self._bundle.name if self._bundle else "")
            chunks = [self._invoke_bucketed(inputs[i:i + cap])
                      for i in range(0, n, cap)]
            return [TensorMemory(jnp.concatenate(
                        [c[j].device(self._device) for c in chunks]))
                    for j in range(len(chunks[0]))]
        if self._resize is not None:
            arrays = [self._resize_region(m) for m in inputs]
        else:
            arrays = [m.device(self._device) for m in inputs]
        shapes = {tuple(a.shape) for a in arrays}
        if len(shapes) != 1:
            raise ValueError(
                f"bucketed invoke needs same-shape tensors, got {shapes} "
                "(add custom=\"resize=H:W\" for image regions)")
        bucket = -(-n // self._bucket) * self._bucket
        tn = _tune.TUNE_HOOK
        if tn is not None and bucket * 2 <= cap:
            # rung choice: the minimal rung pads least but one rung up
            # halves the distinct compiled shapes under jittery arrival
            # counts — store/model resolution only (never a sweep: this
            # is a per-frame path)
            rowbytes = float(arrays[0].nbytes) if arrays else 0.0
            rung = tn.pick(
                "xla_bucket_rung", _tune.device_kind(),
                self._bundle.name if self._bundle else "xla",
                _tune.shape_sig(("rung", bucket)),
                candidates=(bucket, bucket * 2), default=bucket,
                features=lambda r: (0.0, r * rowbytes * 2.0))
            if isinstance(rung, (int, float)) \
                    and bucket <= int(rung) <= cap:
                bucket = int(rung)
        _sched_tel.record_bucket_hit(bucket - n)
        if not hasattr(self, "_stack_fn"):
            # stack+pad inside one jit so the pad constant folds and the
            # whole prep is a single dispatch
            self._stack_fn = jax.jit(
                lambda pad_rows, *xs: jnp.concatenate(
                    [jnp.stack(xs),
                     jnp.zeros((pad_rows,) + xs[0].shape, xs[0].dtype)]),
                static_argnums=0)
        batch = self._stack_fn(bucket - n, *arrays)
        with self._lock:
            prof = _profile.DISPATCH_HOOK
            if prof is not None:
                outs = prof.dispatch(self, [batch])
            else:
                outs = self._jitted(batch)
        if self._sync:
            for o in outs:
                o.block_until_ready()
        return [TensorMemory(o[:n]) for o in outs]

    #: sched/engine.py gates its ``donate=True`` on this attribute so a
    #: filter without the donating twin never sees an unexpected kwarg
    #: (which would demote it to serial fallback forever)
    supports_donate_coalesce = True

    def invoke_coalesced(
            self, groups: Sequence[Sequence[TensorMemory]],
            donate: bool = False
    ) -> List[Sequence[TensorMemory]]:
        """Sched-engine coalesced dispatch: several tenants' work items
        with identical input signatures execute as ONE device batch and
        scatter back per item (sched/engine.py ``_dispatch``).

        The DeviceEngine only coalesces items whose (shape, dtype)
        signatures match exactly, so every group here is uniform: for
        bucketed filters the groups flatten straight through
        ``_invoke_bucketed``; for batch-led models each input position
        concatenates along axis 0, giving at most ``max_coalesce``
        distinct batch shapes (a bounded compile set). Raises when the
        model's outputs are not batch-led — the engine then falls back
        to serial invokes (``sched.coalesce_fallback``).

        ``donate=True`` dispatches through the donating jit twin: the
        concatenated batch buffer is freshly allocated here and read by
        nobody afterwards, so XLA may reuse it for outputs — halving
        peak HBM for the dispatch. The callers' own input buffers are
        never donated (concatenate copies). Ignored on the bucketed and
        single-group paths."""
        import jax.numpy as jnp

        if len(groups) == 1:
            return [self.invoke(groups[0])]
        if self._bucket > 0:
            counts = [len(g) for g in groups]
            flat = [m for g in groups for m in g]
            stacked = self._invoke_bucketed(flat)
            results: List[Sequence[TensorMemory]] = []
            off = 0
            for cnt in counts:
                results.append(
                    [TensorMemory(o.device(self._device)[off:off + cnt])
                     for o in stacked])
                off += cnt
            return results
        npos = len(groups[0])
        if any(len(g) != npos for g in groups):
            raise ValueError("coalesce: input arity mismatch across items")
        rows = [int(g[0].shape[0]) for g in groups]
        total = sum(rows)
        arrays = [jnp.concatenate([g[j].device(self._device)
                                   for g in groups])
                  for j in range(npos)]
        fn = self._jitted
        if donate:
            fn = getattr(self, "_jitted_donate", None) or fn
        with self._lock:
            prof = _profile.DISPATCH_HOOK
            if prof is not None:
                outs = prof.dispatch(self, arrays, fn=fn)
            else:
                outs = fn(*arrays)
        if donate:
            # the donated concat buffers are dead: drop the references
            # so nothing downstream can observe them
            del arrays
        if self._sync:
            for o in outs:
                o.block_until_ready()
        scattered: List[List[TensorMemory]] = [[] for _ in groups]
        for o in outs:
            if getattr(o, "ndim", 0) == 0 or o.shape[0] != total:
                raise ValueError(
                    "coalesce: output not batch-led; cannot scatter "
                    f"(shape {getattr(o, 'shape', ())}, rows {total})")
            off = 0
            for i, cnt in enumerate(rows):
                scattered[i].append(TensorMemory(o[off:off + cnt]))
                off += cnt
        return scattered

    def _resize_region(self, mem: TensorMemory):
        """Bilinear-resize a variable-size region to the static target with a
        BOUNDED compile-shape set: the region is zero-padded (host-side) to
        the next power-of-two extents, and a gather-based bilinear kernel —
        keyed only on the padded shape — samples the true (h, w) extent
        passed as runtime scalars. Matches jax.image.resize(antialias=False)
        (tflite resize_bilinear semantics); the padding is never sampled."""
        import jax
        import jax.numpy as jnp

        arr = mem.host()
        h, w = arr.shape[0], arr.shape[1]
        hp = 1 << max(3, (h - 1).bit_length())
        wp = 1 << max(3, (w - 1).bit_length())
        padded = np.zeros((hp, wp) + arr.shape[2:], arr.dtype)
        padded[:h, :w] = arr
        if not hasattr(self, "_region_resize_fn"):
            th, tw = self._resize

            def region_resize(p, hw):
                trailing = p.shape[2:]
                p = p.reshape(p.shape[0], p.shape[1], -1).astype(jnp.float32)
                hf = hw[0].astype(jnp.float32)
                wf = hw[1].astype(jnp.float32)
                ys = jnp.clip((jnp.arange(th) + 0.5) * hf / th - 0.5,
                              0.0, hf - 1.0)
                xs = jnp.clip((jnp.arange(tw) + 0.5) * wf / tw - 0.5,
                              0.0, wf - 1.0)
                y0 = jnp.floor(ys).astype(jnp.int32)
                x0 = jnp.floor(xs).astype(jnp.int32)
                y1 = jnp.minimum(y0 + 1, hw[0] - 1)
                x1 = jnp.minimum(x0 + 1, hw[1] - 1)
                wy = (ys - y0)[:, None, None]
                wx = (xs - x0)[None, :, None]
                a = p[y0[:, None], x0[None, :]]
                b = p[y0[:, None], x1[None, :]]
                c = p[y1[:, None], x0[None, :]]
                d = p[y1[:, None], x1[None, :]]
                out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
                       + c * wy * (1 - wx) + d * wy * wx)
                return out.reshape((th, tw) + trailing)

            self._region_resize_fn = jax.jit(region_resize)
        return self._region_resize_fn(padded, np.array([h, w], np.int32))

    # -- events -------------------------------------------------------------- #
    def reload_model(self, model: Any) -> None:
        """Hot swap: same I/O contract required (reference RELOAD semantics)."""
        opts = self.props.custom_dict() if self.props else {}
        new_bundle = self._maybe_quantize(resolve_model(model, opts), opts)
        old_in, old_out = self._in_info, self._out_info
        self._bundle = new_bundle
        self._refresh_device()
        self._build_jit()
        if old_in is not None:
            new_out = self._infer_out_info(old_in)
            if old_out is not None and not new_out.is_compatible(old_out):
                raise ValueError(
                    f"reload rejected: output info changed {old_out} -> {new_out}")
            self._out_info = new_out
        log.info("xla-tpu reloaded model=%s", new_bundle.name)
