"""``nnstreamer_python`` compat shim for the reference's custom scripts.

The reference's python3 subplugin injects a helper module
(``import nnstreamer_python as nns`` — ext/nnstreamer/extra/
nnstreamer_python3_helper.cc) whose ``TensorShape`` carries dims in the
reference's innermost-first order plus a numpy dtype. Its script contract
(tests/test_models/models/passthrough.py / scaler.py):

  * ``getInputDim() / getOutputDim() -> [nns.TensorShape, ...]``
  * ``setInputDim([TensorShape]) -> [TensorShape]``
  * ``invoke(input_list) -> output_list`` over FLAT (raveled) arrays —
    scripts reshape via ``dims[::-1]`` themselves
  * constructor receives the ``custom=`` string as ``*args``

Installing this shim under ``sys.modules['nnstreamer_python']`` lets the
reference's OWN scripts serve unmodified; filters/custom.py detects the
flavor by the presence of ``getInputDim``/``setInputDim``.
"""

from __future__ import annotations

import sys
from typing import Any, List, Optional, Sequence

import numpy as np

from ..core.types import TensorDType, TensorInfo, TensorsInfo


class TensorShape:
    """dims (innermost-first, MUTABLE list — scaler.py edits it in place)
    + numpy element type."""

    def __init__(self, dims: Sequence[int], type: Any = np.uint8):  # noqa: A002
        self._dims = [int(d) for d in dims]
        self._type = np.dtype(type)

    def getDims(self) -> List[int]:  # noqa: N802 — reference API names
        return self._dims

    def getType(self) -> np.dtype:  # noqa: N802
        return self._type

    def setDims(self, dims: Sequence[int]) -> None:  # noqa: N802
        self._dims = [int(d) for d in dims]

    def __repr__(self) -> str:
        return f"TensorShape({self._dims}, {self._type})"


def install_shim() -> None:
    """Make ``import nnstreamer_python`` resolve to this module."""
    sys.modules.setdefault("nnstreamer_python", sys.modules[__name__])


def shapes_to_info(shapes: Optional[Sequence[TensorShape]]
                   ) -> Optional[TensorsInfo]:
    if not shapes:
        return None
    infos = []
    for s in shapes:
        dims = [int(d) for d in s.getDims()]
        while len(dims) > 1 and dims[-1] == 1:
            dims.pop()  # reference pads rank to 4 with 1s
        # a 0 dim (script bug) is NOT stripped: TensorInfo rejects it
        infos.append(TensorInfo(tuple(dims),
                                TensorDType.parse(np.dtype(s.getType()))))
    return TensorsInfo(tuple(infos))


def info_to_shapes(info: TensorsInfo) -> List[TensorShape]:
    out = []
    for t in info:
        dims = list(t.dims) + [1] * (4 - len(t.dims))
        out.append(TensorShape(dims, t.dtype.np_dtype))
    return out
