"""framework=torch — TorchScript/nn.Module execution on CPU.

Reference equivalent: tensor_filter_pytorch.cc (libtorch script modules).
This exists for interop/parity — models whose source of truth is a
TorchScript file; the TPU path is framework=xla-tpu. Torch here is CPU-only
(no CUDA in the image); heavy workloads belong on the XLA backend.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.types import TensorsInfo
from .base import FilterFramework, FilterProps, register_filter


@register_filter
class TorchFilter(FilterFramework):
    NAME = "torch"
    ALIASES = ("pytorch",)
    #: torch convnets consume channel-first data natively, so declaring
    #: inputlayout/outputlayout=NCHW is a correct no-op (the data already
    #: matches the model) — accept it rather than reject at open
    SUPPORTS_LAYOUT = True
    ALLOCATE_IN_INVOKE = True

    def __init__(self) -> None:
        super().__init__()
        self._module: Any = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        import torch

        model = props.model
        if isinstance(model, str):
            if not os.path.isfile(model):
                raise FileNotFoundError(model)
            self._module = torch.jit.load(model, map_location="cpu")
        elif isinstance(model, torch.nn.Module):
            self._module = model
        else:
            raise ValueError(f"torch: unsupported model {model!r}")
        self._module.eval()
        self._in_info = props.input_info
        self._out_info = props.output_info

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        import torch

        self._in_info = in_info
        with torch.no_grad():
            dummies = [torch.zeros(*i.shape,
                                   dtype=_torch_dtype(i.dtype.np_dtype))
                       for i in in_info]
            out = self._module(*dummies)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        from ..core.types import TensorInfo

        self._out_info = TensorsInfo(tuple(
            TensorInfo.from_shape(tuple(o.shape) or (1,), np.dtype(str(o.numpy().dtype)))
            for o in outs))
        return self._out_info

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        import torch

        with torch.no_grad():
            tensors = [torch.from_numpy(np.ascontiguousarray(m.host()))
                       for m in inputs]
            out = self._module(*tensors)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [TensorMemory(o.numpy()) for o in outs]


def _torch_dtype(np_dtype: np.dtype):
    import torch

    return torch.from_numpy(np.zeros(1, np_dtype)).dtype
