"""framework=torch — TorchScript/nn.Module execution on CPU.

Reference equivalent: tensor_filter_pytorch.cc (libtorch script modules).
This exists for interop/parity — models whose source of truth is a
TorchScript file; the TPU path is framework=xla-tpu. Torch here is CPU-only
(no CUDA in the image); heavy workloads belong on the XLA backend.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.types import TensorsInfo
from .base import FilterFramework, FilterProps, register_filter


@register_filter
class TorchFilter(FilterFramework):
    NAME = "torch"
    ALIASES = ("pytorch",)
    #: torch convnets consume channel-first data natively, so declaring
    #: inputlayout/outputlayout=NCHW is a correct no-op (the data already
    #: matches the model) — accept it rather than reject at open
    SUPPORTS_LAYOUT = True
    ALLOCATE_IN_INVOKE = True

    def __init__(self) -> None:
        super().__init__()
        self._module: Any = None
        self._out_expect: Optional[list] = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        import torch

        model = props.model
        if isinstance(model, str):
            if not os.path.isfile(model):
                raise FileNotFoundError(model)
            from ..models.torch_legacy import is_legacy_torchscript, load_legacy_torchscript

            if is_legacy_torchscript(model):
                # torch-1.0-era zip (model.json + arena code) that modern
                # torch.jit.load rejects; executed as code, same trust
                # model as torch.jit.load itself
                self._module = load_legacy_torchscript(model)
            else:
                try:
                    self._module = torch.jit.load(model, map_location="cpu")
                except RuntimeError as e:
                    raise RuntimeError(
                        f"torch: failed to load {model!r} as TorchScript "
                        f"(not a legacy-format zip either): {e}") from e
        elif isinstance(model, torch.nn.Module):
            self._module = model
        else:
            raise ValueError(f"torch: unsupported model {model!r}")
        self._module.eval()
        self._in_info = props.input_info
        self._out_info = props.output_info
        self._refresh_out_expect()

    def _refresh_out_expect(self) -> None:
        if self._out_info is None:
            self._out_expect = None
        else:
            self._out_expect = [
                (int(np.prod(i.shape)), i.dtype.np_dtype) for i in self._out_info]

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        import torch

        self._in_info = in_info
        with torch.no_grad():
            dummies = [torch.zeros(*i.shape,
                                   dtype=_torch_dtype(i.dtype.np_dtype))
                       for i in in_info]
            out = self._module(*dummies)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        from ..core.types import TensorInfo

        actual = TensorsInfo(tuple(
            TensorInfo.from_shape(tuple(o.shape) or (1,), np.dtype(str(o.numpy().dtype)))
            for o in outs))
        if self._out_info is not None:
            # declared output props must agree with what the module produces
            # (reference rejects mismatched output= at negotiation,
            # tensor_filter_pytorch.cc getOutputDim/validation)
            for i, (a, d) in enumerate(zip(actual, self._out_info)):
                if (int(np.prod(a.shape)) != int(np.prod(d.shape))
                        or a.dtype.np_dtype != d.dtype.np_dtype):
                    raise RuntimeError(
                        f"torch: declared output {i} {d.shape} {d.dtype.name} "
                        f"!= model output {a.shape} {a.dtype.name}")
            if len(actual) != len(self._out_info):
                raise RuntimeError(
                    f"torch: model produces {len(actual)} outputs, "
                    f"props declare {len(self._out_info)}")
        else:
            self._out_info = actual
        self._refresh_out_expect()
        return self._out_info

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        import torch

        with torch.no_grad():
            tensors = [torch.from_numpy(np.ascontiguousarray(m.host()))
                       for m in inputs]
            out = self._module(*tensors)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        mems = [TensorMemory(o.numpy()) for o in outs]
        if self._out_expect is not None:
            # reference pytorch filter rejects an invoke whose produced
            # tensors disagree with the declared output properties
            # (tensor_filter_pytorch.cc processIFs/validation path)
            if len(mems) != len(self._out_expect):
                raise RuntimeError(
                    f"torch: model produced {len(mems)} tensors, "
                    f"props declare {len(self._out_expect)}")
            for i, (m, (count, dt)) in enumerate(zip(mems, self._out_expect)):
                host = m.host()
                if host.size != count or host.dtype != dt:
                    raise RuntimeError(
                        f"torch: output {i} is {tuple(host.shape)} {host.dtype}"
                        f", props declare {count} elements of {dt}")
        return mems


def _torch_dtype(np_dtype: np.dtype):
    import torch

    return torch.from_numpy(np.zeros(1, np_dtype)).dtype
