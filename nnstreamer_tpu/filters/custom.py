"""Custom filter backends: custom-easy (in-app callables) and python3
(user script files).

Reference equivalents:
 * custom-easy — register a C callback + static I/O info in-app
   (include/tensor_filter_custom_easy.h:25-74). Ours registers a Python
   callable over numpy/jax arrays.
 * python3 — load a user .py defining ``class CustomFilter`` with
   getInputDimension/getOutputDimension/setInputDimension/invoke
   (tensor_filter_python3.cc:85-135,224-273). Same class contract here,
   numpy in/out.
"""

from __future__ import annotations

import importlib.util
import os
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.registry import SubpluginType, get_subplugin, register_subplugin
from ..core.types import TensorsInfo
from .base import FilterFramework, FilterProps, register_filter

# --------------------------------------------------------------------------- #
# custom-easy
# --------------------------------------------------------------------------- #

_easy_lock = threading.Lock()


def register_custom_easy(name: str, fn: Callable[..., Any],
                         in_info: Any, out_info: Any) -> None:
    """Register an in-app model: ``fn(*arrays) -> array(s)`` with fixed I/O.

    ``in_info``/``out_info`` accept TensorsInfo or ("dims", "types") tuples.
    Use as: ``tensor_filter framework=custom-easy model=<name>``.
    """
    ii = in_info if isinstance(in_info, TensorsInfo) else TensorsInfo.from_strings(*in_info)
    oi = out_info if isinstance(out_info, TensorsInfo) else TensorsInfo.from_strings(*out_info)
    register_subplugin(SubpluginType.EASY_CUSTOM, name,
                       {"fn": fn, "in": ii, "out": oi}, replace=True)


def unregister_custom_easy(name: str) -> None:
    from ..core.registry import unregister_subplugin

    unregister_subplugin(SubpluginType.EASY_CUSTOM, name)


@register_filter
class CustomEasyFilter(FilterFramework):
    NAME = "custom-easy"
    ALLOCATE_IN_INVOKE = True
    RUN_WITHOUT_MODEL = False  # model= names the registered callable

    def __init__(self) -> None:
        super().__init__()
        self._entry: Optional[Dict[str, Any]] = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        name = props.model if isinstance(props.model, str) else None
        if name is None:
            raise ValueError("custom-easy: model= must name a registered callable")
        entry = get_subplugin(SubpluginType.EASY_CUSTOM, name)
        if entry is None:
            raise ValueError(f"custom-easy: {name!r} is not registered")
        self._entry = entry

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._entry["in"], self._entry["out"]

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        arrays = [m.host() for m in inputs]
        out = self._entry["fn"](*arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [TensorMemory(np.asarray(o) if not _is_jax(o) else o) for o in outs]


def _is_jax(x: Any) -> bool:
    return type(x).__module__.startswith("jax")


# --------------------------------------------------------------------------- #
# python3 script filter
# --------------------------------------------------------------------------- #

@register_filter
class Python3Filter(FilterFramework):
    """framework=python3 model=/path/to/script.py

    The script defines ``class CustomFilter`` with:
      * ``getInputDimension() -> (dims_str, types_str)`` (or TensorsInfo)
      * ``getOutputDimension() -> (dims_str, types_str)``
      * optional ``setInputDimension(in_info) -> out_info``
      * ``invoke(*arrays) -> array(s)``
    An optional module-level ``make_filter(options_dict)`` constructs it.
    """

    NAME = "python3"
    ALIASES = ("python",)
    ALLOCATE_IN_INVOKE = True

    def __init__(self) -> None:
        super().__init__()
        self._obj: Any = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        path = props.model_path
        if not path or not os.path.isfile(path):
            raise FileNotFoundError(f"python3 filter script not found: {path}")
        spec = importlib.util.spec_from_file_location(
            f"nns_tpu_pyfilter_{abs(hash(path))}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if hasattr(mod, "make_filter"):
            self._obj = mod.make_filter(props.custom_dict())
        elif hasattr(mod, "CustomFilter"):
            self._obj = mod.CustomFilter()
        else:
            raise ValueError(f"{path}: must define CustomFilter or make_filter")

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        ii = oi = None
        if hasattr(self._obj, "getInputDimension"):
            ii = _coerce(self._obj.getInputDimension())
        if hasattr(self._obj, "getOutputDimension"):
            oi = _coerce(self._obj.getOutputDimension())
        return ii, oi

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        if hasattr(self._obj, "setInputDimension"):
            return _coerce(self._obj.setInputDimension(in_info))
        return super().set_input_info(in_info)

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        arrays = [m.host() for m in inputs]
        out = self._obj.invoke(*arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [TensorMemory(np.asarray(o)) for o in outs]


def _coerce(v: Any) -> Optional[TensorsInfo]:
    if v is None or isinstance(v, TensorsInfo):
        return v
    if isinstance(v, (tuple, list)) and len(v) == 2 and isinstance(v[0], str):
        return TensorsInfo.from_strings(v[0], v[1])
    raise ValueError(f"bad dimension spec {v!r}")
