"""Custom filter backends: custom-easy (in-app callables) and python3
(user script files).

Reference equivalents:
 * custom-easy — register a C callback + static I/O info in-app
   (include/tensor_filter_custom_easy.h:25-74). Ours registers a Python
   callable over numpy/jax arrays.
 * python3 — load a user .py defining ``class CustomFilter`` with
   getInputDimension/getOutputDimension/setInputDimension/invoke
   (tensor_filter_python3.cc:85-135,224-273). Same class contract here,
   numpy in/out.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.registry import SubpluginType, get_subplugin, register_subplugin
from ..core.types import TensorsInfo
from .base import FilterFramework, FilterProps, register_filter

# --------------------------------------------------------------------------- #
# custom-easy
# --------------------------------------------------------------------------- #

_easy_lock = threading.Lock()


def register_custom_easy(name: str, fn: Callable[..., Any],
                         in_info: Any, out_info: Any) -> None:
    """Register an in-app model: ``fn(*arrays) -> array(s)`` with fixed I/O.

    ``in_info``/``out_info`` accept TensorsInfo or ("dims", "types") tuples.
    Use as: ``tensor_filter framework=custom-easy model=<name>``.
    """
    ii = in_info if isinstance(in_info, TensorsInfo) else TensorsInfo.from_strings(*in_info)
    oi = out_info if isinstance(out_info, TensorsInfo) else TensorsInfo.from_strings(*out_info)
    register_subplugin(SubpluginType.EASY_CUSTOM, name,
                       {"fn": fn, "in": ii, "out": oi}, replace=True)


def unregister_custom_easy(name: str) -> None:
    from ..core.registry import unregister_subplugin

    unregister_subplugin(SubpluginType.EASY_CUSTOM, name)


@register_filter
class CustomEasyFilter(FilterFramework):
    NAME = "custom-easy"
    ALLOCATE_IN_INVOKE = True
    RUN_WITHOUT_MODEL = False  # model= names the registered callable

    def __init__(self) -> None:
        super().__init__()
        self._entry: Optional[Dict[str, Any]] = None

    def open(self, props: FilterProps) -> None:
        super().open(props)
        name = props.model if isinstance(props.model, str) else None
        if name is None:
            raise ValueError("custom-easy: model= must name a registered callable")
        entry = get_subplugin(SubpluginType.EASY_CUSTOM, name)
        if entry is None:
            raise ValueError(f"custom-easy: {name!r} is not registered")
        self._entry = entry

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._entry["in"], self._entry["out"]

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        arrays = [m.host() for m in inputs]
        out = self._entry["fn"](*arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [TensorMemory(np.asarray(o) if not _is_jax(o) else o) for o in outs]


def _is_jax(x: Any) -> bool:
    return type(x).__module__.startswith("jax")


# --------------------------------------------------------------------------- #
# python3 script filter
# --------------------------------------------------------------------------- #

@register_filter
class Python3Filter(FilterFramework):
    """framework=python3 model=/path/to/script.py

    Two script contracts are served:

    * native: ``class CustomFilter`` with
      ``getInputDimension() -> (dims_str, types_str)`` (or TensorsInfo),
      ``getOutputDimension()``, optional ``setInputDimension(in_info) ->
      out_info``, ``invoke(*arrays) -> array(s)``; optional module-level
      ``make_filter(options_dict)`` constructor;
    * the REFERENCE's contract (tensor_filter_python3.cc +
      nnstreamer_python3_helper.cc — its own test scripts passthrough.py
      / scaler.py run unmodified): ``import nnstreamer_python as nns``
      (shimmed by filters/nns_python_compat.py),
      ``getInputDim()/getOutputDim() -> [nns.TensorShape]``,
      ``setInputDim([TensorShape]) -> [TensorShape]``, and
      ``invoke(list_of_flat_arrays) -> list_of_flat_arrays``; the
      ``custom=`` string arrives as a constructor argument. Flavor is
      detected by the presence of ``getInputDim``/``setInputDim``.
    """

    NAME = "python3"
    ALIASES = ("python",)
    ALLOCATE_IN_INVOKE = True

    def __init__(self) -> None:
        super().__init__()
        self._obj: Any = None

    def open(self, props: FilterProps) -> None:
        from .nns_python_compat import install_shim

        super().open(props)
        install_shim()  # scripts may `import nnstreamer_python as nns`
        path = props.model_path
        if not path or not os.path.isfile(path):
            raise FileNotFoundError(f"python3 filter script not found: {path}")
        from ..converters.pyscript import load_script_module

        mod = load_script_module(path)
        if hasattr(mod, "make_filter"):
            self._obj = mod.make_filter(props.custom_dict())
        elif hasattr(mod, "CustomFilter"):
            # reference semantics: custom= splits on spaces into separate
            # constructor args (tensor_filter_python3.cc:275 g_strsplit).
            # Whether the constructor TAKES arguments is decided by its
            # signature, not by catching TypeError (which would mask a
            # genuine failure inside the constructor body).
            import inspect

            args = tuple(props.custom.split()) if props.custom else ()
            if args:
                try:
                    sig = inspect.signature(mod.CustomFilter.__init__)
                    takes_args = len(sig.parameters) > 1 or any(
                        p.kind is inspect.Parameter.VAR_POSITIONAL
                        for p in sig.parameters.values())
                except (TypeError, ValueError):
                    takes_args = True
                if not takes_args:
                    # native-contract no-arg constructor: custom= is
                    # carried by make_filter there, ignore it here
                    args = ()
            self._obj = mod.CustomFilter(*args)
        else:
            raise ValueError(f"{path}: must define CustomFilter or make_filter")
        self._ref_flavor = hasattr(self._obj, "getInputDim") or \
            hasattr(self._obj, "setInputDim")
        self._out_info: Optional[TensorsInfo] = None

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        from .nns_python_compat import shapes_to_info

        ii = oi = None
        if hasattr(self._obj, "getInputDimension"):
            ii = _coerce(self._obj.getInputDimension())
        elif hasattr(self._obj, "getInputDim"):
            ii = shapes_to_info(self._obj.getInputDim())
        if hasattr(self._obj, "getOutputDimension"):
            oi = _coerce(self._obj.getOutputDimension())
        elif hasattr(self._obj, "getOutputDim"):
            oi = shapes_to_info(self._obj.getOutputDim())
        self._out_info = oi or self._out_info
        return ii, oi

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        from .nns_python_compat import info_to_shapes, shapes_to_info

        if hasattr(self._obj, "setInputDimension"):
            return _coerce(self._obj.setInputDimension(in_info))
        if hasattr(self._obj, "setInputDim"):
            out = shapes_to_info(
                self._obj.setInputDim(info_to_shapes(in_info)))
            if out is None:
                raise ValueError("setInputDim rejected the input dims")
            self._out_info = out
            return out
        return super().set_input_info(in_info)

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        arrays = [m.host() for m in inputs]
        if self._ref_flavor:
            # reference helper semantics: ONE list argument of raveled
            # arrays in, a list of raveled arrays out — reshaped here to
            # the declared output dims
            flat = [np.ravel(a) for a in arrays]
            outs = self._obj.invoke(flat)
            mems = []
            for i, o in enumerate(outs):
                o = np.asarray(o)
                if self._out_info is not None and i < len(self._out_info):
                    o = o.reshape(self._out_info[i].shape)
                mems.append(TensorMemory(o))
            return mems
        out = self._obj.invoke(*arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [TensorMemory(np.asarray(o)) for o in outs]


def _coerce(v: Any) -> Optional[TensorsInfo]:
    if v is None or isinstance(v, TensorsInfo):
        return v
    if isinstance(v, (tuple, list)) and len(v) == 2 and isinstance(v[0], str):
        return TensorsInfo.from_strings(v[0], v[1])
    raise ValueError(f"bad dimension spec {v!r}")
