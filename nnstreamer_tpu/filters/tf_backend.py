"""framework=tensorflow — frozen GraphDef (.pb) serving.

Reference equivalent: ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc
(TF C-API session around a frozen graph, inputname/outputname-addressed
feeds/fetches, DT_STRING inputs fed the raw buffer bytes,
tensor_filter_tensorflow.cc:490-530).  This exists for interop — serving the
reference's own ``mnist.pb``/``conv_actions_frozen.pb`` byte-for-byte; TPU
workloads belong on the xla-tpu backend.

TensorFlow is imported lazily at open() so the rest of the framework never
pays its import cost.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import TensorMemory
from ..core.types import TensorInfo, TensorsInfo
from .base import FilterFramework, FilterProps, register_filter


@register_filter
class TensorFlowFilter(FilterFramework):
    NAME = "tensorflow"
    ALIASES = ("tensorflow1", "tf")
    ALLOCATE_IN_INVOKE = True

    def __init__(self) -> None:
        super().__init__()
        self._sess: Any = None
        self._graph: Any = None
        self._feed_names: List[str] = []
        self._feed_is_string: List[bool] = []
        self._fetch_names: List[str] = []
        self._out_expect: List[tuple] = []

    def open(self, props: FilterProps) -> None:
        super().open(props)
        import tensorflow as tf  # noqa: PLC0415 — heavy, open()-time only

        path = props.model_path
        if not path or not os.path.isfile(path):
            raise FileNotFoundError(f"tensorflow: model file {path!r}")
        gd = tf.compat.v1.GraphDef()
        try:
            with open(path, "rb") as f:
                gd.ParseFromString(f.read())
        except Exception as e:
            raise RuntimeError(
                f"tensorflow: {path!r} is not a frozen GraphDef: {e}") from e
        self._graph = tf.Graph()
        with self._graph.as_default():
            tf.import_graph_def(gd, name="")

        self._in_info = props.input_info
        self._out_info = props.output_info
        if (self._in_info is None or self._out_info is None
                or any(t.name is None for t in self._in_info)
                or any(t.name is None for t in self._out_info)):
            # the reference requires explicit names for the tensorflow
            # backend (tensor_filter_tensorflow.cc validateTensor asserts
            # the named op exists; there is no name-less introspection)
            raise ValueError(
                "tensorflow: input/output names are required "
                "(inputname=/outputname= with input=/inputtype=/output=/outputtype=)")

        self._feed_names, self._feed_is_string = [], []
        for t in self._in_info:
            op = self._op_or_raise(t.name)
            dtype = op.outputs[0].dtype
            self._feed_is_string.append(dtype == tf.string)
            if dtype != tf.string and dtype.as_numpy_dtype != t.dtype.np_dtype:
                raise ValueError(
                    f"tensorflow: input {t.name!r} is {dtype.name} in the "
                    f"graph, props declare {t.dtype.name}")
            self._feed_names.append(t.name + ":0")
        self._fetch_names = []
        for t in self._out_info:
            op = self._op_or_raise(t.name)
            dtype = op.outputs[0].dtype
            if dtype != tf.string and dtype.as_numpy_dtype != t.dtype.np_dtype:
                raise ValueError(
                    f"tensorflow: output {t.name!r} is {dtype.name} in the "
                    f"graph, props declare {t.dtype.name}")
            shape = op.outputs[0].shape
            if shape.rank is not None:
                known = [int(d) for d in shape if d is not None]
                declared = int(np.prod(t.shape))
                if known and len(known) == shape.rank \
                        and int(np.prod(known)) != declared:
                    raise ValueError(
                        f"tensorflow: output {t.name!r} is {shape} in the "
                        f"graph ({int(np.prod(known))} elements), props "
                        f"declare {declared}")
            self._fetch_names.append(t.name + ":0")
        # per-output (element count, dtype) for invoke-time validation of
        # graphs whose static shape is unknown until run
        self._out_expect = [
            (int(np.prod(t.shape)), t.dtype.np_dtype) for t in self._out_info]

        config = None
        if props.num_threads > 0:
            config = tf.compat.v1.ConfigProto(
                intra_op_parallelism_threads=props.num_threads,
                inter_op_parallelism_threads=props.num_threads)
        self._sess = tf.compat.v1.Session(graph=self._graph, config=config)

    def _op_or_raise(self, name: str):
        try:
            return self._graph.get_operation_by_name(name)
        except KeyError:
            raise ValueError(
                f"tensorflow: graph has no operation named {name!r}") from None

    def get_model_info(self) -> Tuple[Optional[TensorsInfo], Optional[TensorsInfo]]:
        return self._in_info, self._out_info

    def set_input_info(self, in_info: TensorsInfo) -> TensorsInfo:
        # names came from props; only dims/types may be renegotiated
        named = TensorsInfo(tuple(
            TensorInfo(dims=i.dims, dtype=i.dtype, name=d.name)
            for i, d in zip(in_info, self._in_info)))
        self._in_info = named
        return self._out_info

    def invoke(self, inputs: Sequence[TensorMemory]) -> Sequence[TensorMemory]:
        feed = {}
        for name, is_str, mem, info in zip(
                self._feed_names, self._feed_is_string, inputs, self._in_info):
            host = mem.host()
            if is_str:
                # DT_STRING op: the raw buffer bytes become one scalar
                # string element (tensor_filter_tensorflow.cc:502-530)
                feed[name] = np.array(np.ascontiguousarray(host).tobytes(),
                                      dtype=object)
            else:
                feed[name] = np.ascontiguousarray(host).reshape(info.shape)
        outs = self._sess.run(self._fetch_names, feed_dict=feed)
        mems = []
        for i, (o, (count, dt)) in enumerate(zip(outs, self._out_expect)):
            arr = np.asarray(o)
            if arr.size != count or arr.dtype != dt:
                # declared output props must match what the session produced
                # (the reference rejects mismatched output=, runTest 3F_n)
                raise RuntimeError(
                    f"tensorflow: output {i} is {arr.shape} {arr.dtype}, "
                    f"props declare {count} elements of {dt}")
            mems.append(TensorMemory(arr))
        return mems

    def close(self) -> None:
        if self._sess is not None:
            self._sess.close()
            self._sess = None
        self._graph = None
        super().close()
