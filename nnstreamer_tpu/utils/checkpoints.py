"""Model parameter (de)serialization.

The reference treats models as opaque backend files; our native format is a
flax param pytree serialized with msgpack (``.msgpack``) or an orbax
checkpoint directory. This also backs model hot-reload
(``is-updatable`` + RELOAD_MODEL): swap in new params without pipeline
restart.
"""

from __future__ import annotations

import os
from typing import Any


def save_variables(path: str, variables: Any) -> None:
    if path.endswith(".msgpack"):
        from flax import serialization

        with open(path, "wb") as f:
            f.write(serialization.to_bytes(variables))
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        # checkpoints are save-points: overwriting an existing path is the
        # normal save->load->save cycle (orbax refuses by default)
        ckptr.save(os.path.abspath(path), variables, force=True)
        ckptr.wait_until_finished()


def load_variables(path: str, template: Any) -> Any:
    if path.endswith(".msgpack"):
        from flax import serialization

        with open(path, "rb") as f:
            return serialization.from_bytes(template, f.read())
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), target=template)
