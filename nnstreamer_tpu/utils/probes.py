"""Performance probes: per-phase H2D/compute/D2H splits, FLOPs, MFU.

The reference exposes per-filter invoke latency / throughput as runtime
props (tensor_filter.c:366-400, tensor_filter_common.c:967-981) but cannot
say *where* an invoke's time goes.  On TPU — especially through a
high-RTT tunnel — a synchronous per-invoke number is dominated by the
round-trip, not by chip time, so these probes measure each phase the way
streaming pipelines actually run it: **pipelined**, K transfers/invokes in
flight, reporting the amortized per-frame cost.  A separate single
synchronous round-trip isolates the RTT itself.

``model_flops`` asks XLA's compiled-cost analysis for the per-invoke FLOP
count; ``mfu`` relates achieved FLOP/s to the chip's peak (bf16 MXU).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

#: per-chip peak dense-matmul FLOP/s used for MFU accounting, keyed by a
#: substring of jax device_kind. bf16 MXU numbers (public chip specs).
PEAK_FLOPS = {
    "v5 lite": 197e12,  # TPU v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,  # Trillium
    "cpu": 1e11,  # nominal; MFU on CPU is not meaningful
}
DEFAULT_PEAK = 197e12

#: per-chip peak HBM bandwidth (bytes/s), same device_kind keying —
#: the roofline's memory ceiling (public chip specs).
PEAK_HBM_BW = {
    "v5 lite": 819e9,  # TPU v5e
    "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6": 1640e9,  # Trillium
    "cpu": 50e9,  # nominal DDR figure; roofline on CPU is not meaningful
}
DEFAULT_HBM_BW = 819e9


def _by_device_kind(table: Dict[str, float], default: float,
                    device: Any = None) -> float:
    import jax

    device = device or jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or str(device)).lower()
    for key, val in table.items():
        if key in kind:
            return val
    return default


def chip_peak_flops(device: Any = None) -> float:
    return _by_device_kind(PEAK_FLOPS, DEFAULT_PEAK, device)


def chip_peak_hbm_bw(device: Any = None) -> float:
    return _by_device_kind(PEAK_HBM_BW, DEFAULT_HBM_BW, device)


def ridge_intensity(device: Any = None) -> float:
    """Roofline ridge point (FLOPs/byte): operational intensity below
    this is memory-bound, above it compute-bound, on this chip."""
    return chip_peak_flops(device) / chip_peak_hbm_bw(device)


def model_flops(fn: Callable, *example_args: Any) -> Optional[float]:
    """Per-invoke FLOPs from XLA's compiled cost analysis (None if the
    backend doesn't expose it)."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*example_args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returned [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        return flops if flops > 0 else None
    except Exception:
        return None


def mfu(flops_per_frame: Optional[float], fps: float,
        device: Any = None) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over chip peak. Only an
    *MFU* when fps is measured over device-busy time (a saturating or
    synced loop). For an end-to-end pipeline rate — where batching
    budgets, tunnel RTT, and host stages sit between frames — use
    ``pipeline_util``, which is the same ratio under its honest name."""
    if not flops_per_frame or not np.isfinite(fps):
        return None
    return flops_per_frame * fps / chip_peak_flops(device)


def pipeline_util(flops_per_frame: Optional[float], fps: float,
                  device: Any = None) -> Optional[float]:
    """Fraction of chip peak consumed by a pipeline running end-to-end
    at ``fps``: (per-frame FLOPs × fps) / peak. Deliberately NOT called
    MFU: wall-clock fps includes everything that is not the chip
    (batch-formation budgets, queue waits, host pre/post, wire RTT), so
    tiny values mean "the chip is mostly idle between frames", not "the
    model runs inefficiently"."""
    return mfu(flops_per_frame, fps, device)


def _pipelined(run_one: Callable[[int], Any], k: int,
               finish: Callable[[Sequence[Any]], None]) -> float:
    """Launch k ops back-to-back, block at the end; per-op seconds."""
    outs = [run_one(i) for i in range(k)]
    finish(outs)
    t0 = time.perf_counter()
    outs = [run_one(i) for i in range(k)]
    finish(outs)
    return (time.perf_counter() - t0) / k


def phase_split(fn: Callable, example: Sequence[np.ndarray],
                device: Any = None, k: int = 32) -> Dict[str, float]:
    """Amortized per-frame cost of each pipeline phase, in µs:

      * ``rtt_us``     — one synchronous tiny-transfer round trip (the
        latency floor any per-frame sync point pays);
      * ``h2d_us``     — pipelined host→device upload of one input frame;
      * ``compute_us`` — pipelined invoke with inputs already resident;
      * ``d2h_us``     — pipelined device→host readback of the outputs
        (async prefetch, then materialize — the decoder's drain path).

    These are throughput costs: what a deep streaming pipeline pays per
    frame, not what a lone blocking call observes.
    """
    import jax

    device = device or jax.devices()[0]
    jitted = jax.jit(fn)
    host_frames = [np.asarray(a) for a in example]

    # warm compile + resident inputs
    resident = [jax.device_put(a, device) for a in host_frames]
    out = jitted(*resident)
    jax.block_until_ready(out)

    # rtt: single sync round trip of a tiny array
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(np.zeros(4, np.float32), device))
        ts.append(time.perf_counter() - t0)
    rtt = float(np.median(ts))

    h2d = _pipelined(
        lambda i: [jax.device_put(a, device) for a in host_frames],
        k, lambda outs: jax.block_until_ready(outs))

    compute = _pipelined(
        lambda i: jitted(*resident),
        k, lambda outs: jax.block_until_ready(outs))

    def read_back(outs):
        flat = []
        for o in outs:
            flat.extend(o if isinstance(o, (tuple, list)) else [o])
        for o in flat:
            try:
                o.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
        for o in flat:
            np.asarray(o)

    d2h = _pipelined(lambda i: jitted(*resident), k, read_back) - compute
    return {
        "rtt_us": round(rtt * 1e6, 1),
        "h2d_us": round(h2d * 1e6, 1),
        "compute_us": round(compute * 1e6, 1),
        "d2h_us": round(max(d2h, 0.0) * 1e6, 1),
    }


def tpu_smoke(device: Any = None) -> Dict[str, str]:
    """On-chip smoke lane: exercises the paths the CPU test suite pins to
    the virtual mesh and reports pass/fail per item (VERDICT r2 weak #7).

    Items: device-resident element flow, decoder submit/complete device
    reduce, bucketed dynamic-count invoke, donate=true, non-interpret
    Pallas kernel.
    """
    import jax
    import jax.numpy as jnp

    device = device or jax.devices()[0]
    results: Dict[str, str] = {"device": str(device)}

    def run(name: str, thunk: Callable[[], None]) -> None:
        try:
            thunk()
            results[name] = "pass"
        except Exception as e:  # noqa: BLE001 - report, don't crash bench
            results[name] = f"FAIL: {type(e).__name__}: {e}"[:200]

    def device_resident_flow():
        from fractions import Fraction

        from ..core import Caps
        from ..graph import Pipeline

        p = Pipeline()
        frames = [np.random.default_rng(i).integers(0, 255, (16, 16, 3))
                  .astype(np.uint8) for i in range(4)]
        src = p.add_new("appsrc", caps=Caps("video/x-raw", {
            "format": "RGB", "width": 16, "height": 16,
            "framerate": Fraction(0, 1)}), data=frames)
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter", framework="xla-tpu",
                         model="zoo://scaler?dims=3:16:16:1&types=uint8"
                               "&scale=2")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, filt, sink)
        p.run(timeout=300)
        assert sink.num_buffers == 4
        assert sink.buffers[0].memories[0].is_device, "output left device"

    def submit_complete():
        from ..core.buffer import Buffer
        from ..core.types import TensorsConfig, TensorsInfo
        from ..decoders.base import find_decoder

        seg = np.random.default_rng(0).normal(
            size=(1, 8, 8, 5)).astype(np.float32)
        cfg = TensorsConfig(TensorsInfo.from_strings("5:8:8:1", "float32"))
        d = find_decoder("image_segment")()
        d.init({1: "tflite-deeplab"})
        tok = d.submit(Buffer.of(jax.device_put(seg, device)), cfg)
        assert isinstance(tok, tuple), "device reduce path not taken"
        out = d.complete(tok, cfg)
        ref = d.decode(Buffer.of(seg), cfg)
        np.testing.assert_array_equal(out.memories[0].host(),
                                      ref.memories[0].host())

    def bucketed():
        from ..core.buffer import TensorMemory
        from ..filters.base import FilterProps
        from ..filters.xla import XLAFilter

        f = XLAFilter()
        f.open(FilterProps(model="zoo://passthrough", custom="bucket=4"))
        outs = f.invoke([TensorMemory(np.full((3, 3), i, np.float32))
                         for i in range(3)])
        got = outs[0].host()
        assert got.shape == (3, 3, 3)
        np.testing.assert_array_equal(
            got, np.stack([np.full((3, 3), i, np.float32)
                           for i in range(3)]))

    def donate():
        from ..core.buffer import TensorMemory
        from ..filters.base import FilterProps
        from ..filters.xla import XLAFilter

        f = XLAFilter()
        f.open(FilterProps(model="zoo://scaler?scale=3",
                           custom="donate=true,sync=true"))
        x = np.ones((4, 4), np.float32)
        outs = f.invoke([TensorMemory(jax.device_put(x, device))])
        np.testing.assert_allclose(outs[0].host(), x * 3)

    def pallas_compiled():
        from ..ops.pallas.preprocess import _on_tpu, normalize_u8

        assert _on_tpu(), "pallas probe needs the real chip"
        x = jax.device_put(np.arange(256, dtype=np.uint8).reshape(2, 128),
                           device)
        out = np.asarray(normalize_u8(x, scale=1 / 255.0, bias=0.0,
                                      out_dtype=jnp.float32,
                                      interpret=False)).astype(np.float32)
        np.testing.assert_allclose(
            out, np.arange(256, dtype=np.float32).reshape(2, 128) / 255.0,
            rtol=1e-6)

    run("device_resident_flow", device_resident_flow)
    run("decoder_submit_complete", submit_complete)
    run("bucketed_invoke", bucketed)
    run("donate_invoke", donate)
    run("pallas_noninterpret", pallas_compiled)
    return results
