"""Pipeline tracing & profiling.

Reference (SURVEY §5): no in-tree tracer; users attach GstShark tracers
(``interlatency``, ``proctime``) plus per-filter invoke stats. Here the
mechanism is the obs subsystem: ``PipelineTracer`` is a thin consumer
of a ``MetricsRegistry`` — it attaches the same element-chain
instrumentation the live ``/metrics`` exporter uses
(obs/instrument.py), records into a private registry, and renders a
per-run report from its snapshot. One wrapping mechanism, two
consumers; no parallel bookkeeping.

    tracer = PipelineTracer.attach(pipeline)
    pipeline.run()
    print(tracer.report())

``attach(pipeline, spans=True)`` additionally records per-element
spans into a private ``SpanStore`` (obs/tracing.py) — the same store
machinery behind ``/debug/traces`` — and ``span_report()`` renders the
per-element span table. Private means private: neither the global
metrics registry nor the global trace store sees a tracer's data.

``device_trace`` brackets a run with jax.profiler for XLA/TPU
timelines (xprof). When global tracing is enabled it also opens a
``device.xprof`` span carrying the logdir, so an XLA timeline can be
joined to the wire-level trace that was active when profiling started
(``trace_id`` attribute on the context manager after ``__enter__``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..obs import tracing as _tracing
from ..obs.instrument import instrument_pipeline
from ..obs.metrics import MetricsRegistry


class PipelineTracer:
    """Per-run proctime/interlatency report over a private registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 span_store: Optional[_tracing.SpanStore] = None) -> None:
        #: private + always-enabled: a tracer must record even when the
        #: process-global telemetry is off, and must not pollute it
        self.registry = registry or MetricsRegistry(enabled=True)
        #: optional private span store (attach(spans=True))
        self.span_store = span_store

    @classmethod
    def attach(cls, pipeline: Any, spans: bool = False) -> "PipelineTracer":
        store = _tracing.SpanStore(enabled=True) if spans else None
        tracer = cls(span_store=store)
        instrument_pipeline(pipeline, tracer.registry, span_store=store)
        return tracer

    def _stats(self) -> Dict[str, Dict[str, float]]:
        snap = self.registry.snapshot()

        def per_element(name):
            out: Dict[str, Dict[str, float]] = {}
            for s in snap.get(name, {}).get("series", []):
                out[s["labels"]["element"]] = s
            return out

        proc = per_element("nnstpu_pipeline_proctime_seconds")
        inter = per_element("nnstpu_pipeline_interlatency_seconds")
        stats: Dict[str, Dict[str, float]] = {}
        for el in set(proc) | set(inter):
            p = proc.get(el, {"count": 0, "sum": 0.0, "max": 0.0})
            i = inter.get(el, {"count": 0, "sum": 0.0})
            n = int(p["count"])
            stats[el] = {
                "n": n,
                "proctime_us": p["sum"] / max(n, 1) * 1e6,
                "max_us": p["max"] * 1e6,
                "interlatency_us":
                    i["sum"] / max(int(i["count"]), 1) * 1e6,
            }
        return stats

    def report(self) -> str:
        lines = [f"{'element':<24}{'bufs':>7}{'proctime(us)':>14}"
                 f"{'max(us)':>10}{'interlat(us)':>14}"]
        # sorted slowest-mean first: the _stats() source iterates a set
        # union, and a report whose row order changes run to run cannot
        # be diffed (tests/test_tracing.py pins the ordering)
        rows = sorted(self._stats().items(),
                      key=lambda kv: kv[1]["proctime_us"], reverse=True)
        for name, t in rows:
            lines.append(f"{name:<24}{t['n']:>7}{t['proctime_us']:>14.1f}"
                         f"{t['max_us']:>10.1f}{t['interlatency_us']:>14.1f}")
        return "\n".join(lines)

    def span_report(self) -> str:
        """Per-element span table from the private store; requires
        ``attach(pipeline, spans=True)``."""
        if self.span_store is None:
            raise RuntimeError(
                "span_report needs PipelineTracer.attach(pipeline, "
                "spans=True)")
        return _tracing.element_stats_report(self.span_store)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        return self._stats()


class device_trace:
    """Context manager: jax.profiler trace around a pipeline run (view with
    xprof/tensorboard). SURVEY §5 'TPU build: jax.profiler/xprof'.

    With global tracing enabled, the bracket is also a ``device.xprof``
    span (parented on the caller's current span when inside one), so
    ``trace_id`` joins the xprof logdir to a wire-level trace."""

    def __init__(self, logdir: str):
        self.logdir = logdir
        self.trace_id: Optional[str] = None
        self._span = _tracing.NOOP_SPAN

    def __enter__(self):
        import jax

        self._span = _tracing.start_span(
            "device.xprof", parent=_tracing.current_context(),
            attrs={"logdir": self.logdir})
        if self._span.recording:
            self.trace_id = self._span.context.trace_id
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc: Any) -> None:
        import jax

        jax.profiler.stop_trace()
        self._span.end()
