"""Pipeline tracing & profiling.

Reference (SURVEY §5): no in-tree tracer; users attach GstShark tracers
(``interlatency``, ``proctime``) plus per-filter invoke stats. Here the
mechanism is the obs subsystem: ``PipelineTracer`` is a thin consumer
of a ``MetricsRegistry`` — it attaches the same element-chain
instrumentation the live ``/metrics`` exporter uses
(obs/instrument.py), records into a private registry, and renders a
per-run report from its snapshot. One wrapping mechanism, two
consumers; no parallel bookkeeping.

    tracer = PipelineTracer.attach(pipeline)
    pipeline.run()
    print(tracer.report())

``device_trace`` brackets a run with jax.profiler for XLA/TPU
timelines (xprof).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..obs.instrument import instrument_pipeline
from ..obs.metrics import MetricsRegistry


class PipelineTracer:
    """Per-run proctime/interlatency report over a private registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        #: private + always-enabled: a tracer must record even when the
        #: process-global telemetry is off, and must not pollute it
        self.registry = registry or MetricsRegistry(enabled=True)

    @classmethod
    def attach(cls, pipeline: Any) -> "PipelineTracer":
        tracer = cls()
        instrument_pipeline(pipeline, tracer.registry)
        return tracer

    def _stats(self) -> Dict[str, Dict[str, float]]:
        snap = self.registry.snapshot()

        def per_element(name):
            out: Dict[str, Dict[str, float]] = {}
            for s in snap.get(name, {}).get("series", []):
                out[s["labels"]["element"]] = s
            return out

        proc = per_element("nnstpu_pipeline_proctime_seconds")
        inter = per_element("nnstpu_pipeline_interlatency_seconds")
        stats: Dict[str, Dict[str, float]] = {}
        for el in set(proc) | set(inter):
            p = proc.get(el, {"count": 0, "sum": 0.0, "max": 0.0})
            i = inter.get(el, {"count": 0, "sum": 0.0})
            n = int(p["count"])
            stats[el] = {
                "n": n,
                "proctime_us": p["sum"] / max(n, 1) * 1e6,
                "max_us": p["max"] * 1e6,
                "interlatency_us":
                    i["sum"] / max(int(i["count"]), 1) * 1e6,
            }
        return stats

    def report(self) -> str:
        lines = [f"{'element':<24}{'bufs':>7}{'proctime(us)':>14}"
                 f"{'max(us)':>10}{'interlat(us)':>14}"]
        for name, t in self._stats().items():
            lines.append(f"{name:<24}{t['n']:>7}{t['proctime_us']:>14.1f}"
                         f"{t['max_us']:>10.1f}{t['interlatency_us']:>14.1f}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        return self._stats()


class device_trace:
    """Context manager: jax.profiler trace around a pipeline run (view with
    xprof/tensorboard). SURVEY §5 'TPU build: jax.profiler/xprof'."""

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc: Any) -> None:
        import jax

        jax.profiler.stop_trace()
