"""Pipeline tracing & profiling.

Reference (SURVEY §5): no in-tree tracer; users attach GstShark tracers
(``interlatency``, ``proctime``) plus per-filter invoke stats. Here tracing
is in-tree: a ``PipelineTracer`` wraps every element's chain to record
per-element processing time (proctime) and source→element latency
(interlatency), and ``device_trace`` brackets a run with jax.profiler for
XLA/TPU timelines (xprof).

    tracer = PipelineTracer.attach(pipeline)
    pipeline.run()
    print(tracer.report())
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.buffer import Buffer


@dataclass
class ElementTrace:
    name: str
    n: int = 0
    total_ns: int = 0
    max_ns: int = 0
    # interlatency: time from buffer PTS-origin entry into pipeline to entry
    # into this element (needs source stamping wall-clock in buf.meta)
    inter_total_ns: int = 0
    inter_n: int = 0

    @property
    def proctime_us(self) -> float:
        return self.total_ns / max(self.n, 1) / 1000

    @property
    def interlatency_us(self) -> float:
        return self.inter_total_ns / max(self.inter_n, 1) / 1000


class PipelineTracer:
    """Wraps element chains to collect proctime/interlatency per element."""

    def __init__(self) -> None:
        self.traces: Dict[str, ElementTrace] = {}
        self._lock = threading.Lock()

    @classmethod
    def attach(cls, pipeline: Any) -> "PipelineTracer":
        tracer = cls()
        for el in pipeline.elements.values():
            tracer._wrap(el)
        return tracer

    def _wrap(self, el: Any) -> None:
        trace = self.traces.setdefault(el.name, ElementTrace(el.name))
        if el.is_source:
            orig_create = getattr(el, "create", None)
            if orig_create is not None:
                def create_stamped(_orig=orig_create):
                    buf = _orig()
                    if buf is not None:
                        buf.meta.setdefault("trace_t0_ns", time.monotonic_ns())
                    return buf

                el.create = create_stamped
            return
        orig = el._chain_entry

        def timed_chain(pad, buf, _orig=orig, _t=trace):
            now = time.monotonic_ns()
            t0 = buf.meta.get("trace_t0_ns") if isinstance(buf, Buffer) else None
            start = time.monotonic_ns()
            ret = _orig(pad, buf)
            dt = time.monotonic_ns() - start
            with self._lock:
                _t.n += 1
                _t.total_ns += dt
                _t.max_ns = max(_t.max_ns, dt)
                if t0 is not None:
                    _t.inter_n += 1
                    _t.inter_total_ns += now - t0
            return ret

        el._chain_entry = timed_chain

    def report(self) -> str:
        lines = [f"{'element':<24}{'bufs':>7}{'proctime(us)':>14}"
                 f"{'max(us)':>10}{'interlat(us)':>14}"]
        for t in self.traces.values():
            if t.n == 0 and t.inter_n == 0:
                continue
            lines.append(f"{t.name:<24}{t.n:>7}{t.proctime_us:>14.1f}"
                         f"{t.max_ns / 1000:>10.1f}{t.interlatency_us:>14.1f}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        return {t.name: {"n": t.n, "proctime_us": t.proctime_us,
                         "max_us": t.max_ns / 1000,
                         "interlatency_us": t.interlatency_us}
                for t in self.traces.values()}


class device_trace:
    """Context manager: jax.profiler trace around a pipeline run (view with
    xprof/tensorboard). SURVEY §5 'TPU build: jax.profiler/xprof'."""

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc: Any) -> None:
        import jax

        jax.profiler.stop_trace()
