"""ctypes bridge to the native C++ runtime (native/nns_runtime.cpp).

Builds ``libnns_runtime.so`` on demand with g++ (cached beside the source);
every entry point has a pure-Python/numpy fallback so the framework works
without a toolchain. Components: aligned allocator, sparse COO codec, wire
frame header codec, lock-free SPSC ring.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..core.log import logger

log = logger("native")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "nns_runtime.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libnns_runtime.so")


def _build() -> Optional[str]:
    if os.path.isfile(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return _SO
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.info("native runtime build unavailable: %s", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        # signatures
        lib.nns_aligned_alloc.restype = ctypes.c_void_p
        lib.nns_aligned_alloc.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
        lib.nns_aligned_free.argtypes = [ctypes.c_void_p]
        lib.nns_sparse_encode.restype = ctypes.c_int64
        lib.nns_sparse_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.nns_sparse_decode.restype = ctypes.c_int64
        lib.nns_sparse_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64]
        lib.nns_ring_create.restype = ctypes.c_void_p
        lib.nns_ring_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.nns_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.nns_ring_push.restype = ctypes.c_int
        lib.nns_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_uint32]
        lib.nns_ring_pop.restype = ctypes.c_int64
        lib.nns_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64]
        lib.nns_ring_size.restype = ctypes.c_uint64
        lib.nns_ring_size.argtypes = [ctypes.c_void_p]
        lib.nns_wire_header_size.restype = ctypes.c_size_t
        _lib = lib
        log.info("native runtime loaded: %s", so)
        return _lib


def native_available() -> bool:
    return get_lib() is not None


# --------------------------------------------------------------------------- #
# Aligned buffers
# --------------------------------------------------------------------------- #

def aligned_empty(shape, dtype, alignment: int = 64) -> np.ndarray:
    """numpy array over a cacheline-aligned native allocation (falls back to
    numpy's allocator). tensor_allocator.c equivalent."""
    lib = get_lib()
    dtype = np.dtype(dtype)
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dtype.itemsize
    if lib is None or nbytes == 0:
        return np.empty(shape, dtype)
    ptr = lib.nns_aligned_alloc(nbytes, alignment)
    if not ptr:
        return np.empty(shape, dtype)
    buf = (ctypes.c_uint8 * nbytes).from_address(ptr)
    arr = np.frombuffer(buf, dtype=dtype, count=count).reshape(shape)
    # keep the allocation alive & free with the array
    arr = arr.view(_AlignedArray)
    arr._nns_ptr = ptr
    return arr


class _AlignedArray(np.ndarray):
    _nns_ptr = None

    def __array_finalize__(self, obj):
        if obj is not None and not hasattr(self, "_nns_ptr"):
            self._nns_ptr = None

    def __del__(self):
        ptr = getattr(self, "_nns_ptr", None)
        if ptr:
            lib = get_lib()
            if lib is not None:
                lib.nns_aligned_free(ptr)


# --------------------------------------------------------------------------- #
# Sparse codec (native fast path; numpy fallback)
# --------------------------------------------------------------------------- #

def sparse_encode_arrays(dense: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """dense → (uint32 flat indices, values)."""
    dense = np.ascontiguousarray(dense)
    lib = get_lib()
    if lib is None or dense.dtype.itemsize not in (1, 2, 4, 8):
        flat = dense.reshape(-1)
        idx = np.nonzero(flat)[0].astype(np.uint32)
        return idx, flat[idx]
    n = dense.size
    idx = np.empty(n, np.uint32)
    vals = np.empty(n, dense.dtype)
    nnz = lib.nns_sparse_encode(
        dense.ctypes.data, n, dense.dtype.itemsize,
        idx.ctypes.data, vals.ctypes.data, n)
    if nnz < 0:
        raise RuntimeError("sparse encode overflow")
    return idx[:nnz].copy(), vals[:nnz].copy()


def sparse_decode_arrays(indices: np.ndarray, values: np.ndarray,
                         num_elements: int, dtype) -> np.ndarray:
    lib = get_lib()
    dtype = np.dtype(dtype)
    if lib is None or dtype.itemsize not in (1, 2, 4, 8):
        flat = np.zeros(num_elements, dtype)
        flat[indices] = values
        return flat
    out = np.zeros(num_elements, dtype)
    indices = np.ascontiguousarray(indices, np.uint32)
    values = np.ascontiguousarray(values, dtype)
    ret = lib.nns_sparse_decode(indices.ctypes.data, values.ctypes.data,
                                len(indices), dtype.itemsize,
                                out.ctypes.data, num_elements)
    if ret < 0:
        raise ValueError("sparse index out of range")
    return out


# --------------------------------------------------------------------------- #
# SPSC ring
# --------------------------------------------------------------------------- #

class SpscRing:
    """Lock-free single-producer/single-consumer byte-record ring."""

    def __init__(self, capacity_pow2: int = 1024, slot_size: int = 4096):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ring = lib.nns_ring_create(capacity_pow2, slot_size)
        if not self._ring:
            raise RuntimeError("ring allocation failed (capacity must be 2^n)")
        self._slot = slot_size

    def push(self, data: bytes) -> bool:
        ret = self._lib.nns_ring_push(self._ring, data, len(data))
        if ret == -1:
            raise ValueError(f"record {len(data)}B exceeds slot {self._slot}B")
        return ret == 1

    def pop(self) -> Optional[bytes]:
        out = (ctypes.c_uint8 * self._slot)()
        n = self._lib.nns_ring_pop(self._ring, out, self._slot)
        if n == -1:
            return None
        if n == -2:
            raise RuntimeError("slot larger than pop buffer")
        return bytes(out[:n])

    def __len__(self) -> int:
        return int(self._lib.nns_ring_size(self._ring))

    def close(self) -> None:
        if self._ring:
            self._lib.nns_ring_destroy(self._ring)
            self._ring = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
