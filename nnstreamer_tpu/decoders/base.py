"""tensor_decoder subplugin API.

Reference: ``GstTensorDecoderDef`` (nnstreamer_plugin_api_decoder.h:38-97):
subplugins keyed by ``mode=`` with ``option1..optionN`` strings, an output
caps query, and a decode callback. Registered under
``SubpluginType.DECODER``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.registry import SubpluginType, get_subplugin, register_subplugin
from ..core.types import Caps, TensorsConfig


class Decoder:
    """Base decoder. Subclasses set MODE and implement out_caps/decode."""

    MODE = "base"

    def __init__(self) -> None:
        self.options: Dict[int, str] = {}

    def init(self, options: Dict[int, str]) -> None:
        """option1..optionN strings (reference optionN props)."""
        self.options = options

    def option(self, n: int, default: str = "") -> str:
        return self.options.get(n, default)

    def out_caps(self, config: TensorsConfig) -> Caps:
        raise NotImplementedError

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        """Return a new Buffer whose memories hold the decoded media
        (video frame array / utf-8 text bytes / serialized blob)."""
        raise NotImplementedError


def register_decoder(cls: type) -> type:
    register_subplugin(SubpluginType.DECODER, cls.MODE, cls, replace=True)
    for alias in getattr(cls, "ALIASES", ()):
        register_subplugin(SubpluginType.DECODER, alias, cls, replace=True)
    return cls


def find_decoder(mode: str) -> Optional[type]:
    from . import _ensure_builtin_decoders

    _ensure_builtin_decoders()
    return get_subplugin(SubpluginType.DECODER, mode)
