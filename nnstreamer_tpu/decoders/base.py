"""tensor_decoder subplugin API.

Reference: ``GstTensorDecoderDef`` (nnstreamer_plugin_api_decoder.h:38-97):
subplugins keyed by ``mode=`` with ``option1..optionN`` strings, an output
caps query, and a decode callback. Registered under
``SubpluginType.DECODER``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.registry import SubpluginType, get_subplugin, register_subplugin
from ..core.types import Caps, TensorsConfig


class Decoder:
    """Base decoder. Subclasses set MODE and implement out_caps/decode."""

    MODE = "base"

    def __init__(self) -> None:
        self.options: Dict[int, str] = {}

    def init(self, options: Dict[int, str]) -> None:
        """option1..optionN strings (reference optionN props)."""
        self.options = options

    def option(self, n: int, default: str = "") -> str:
        return self.options.get(n, default)

    def out_caps(self, config: TensorsConfig) -> Caps:
        raise NotImplementedError

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        """Return a new Buffer whose memories hold the decoded media
        (video frame array / utf-8 text bytes / serialized blob)."""
        raise NotImplementedError

    # -- pipelined decode (tensor_decoder async_depth) ----------------------- #
    def submit(self, buf: Buffer, config: TensorsConfig) -> Any:
        """Start this frame's async work — device-side reductions and D2H
        copies — and return a token ``complete()`` turns into the decoded
        buffer N frames later. Default: prefetch the raw memories and run
        ``decode`` on host at completion. Decoders whose host output is much
        smaller than their tensor input (argmax masks, box lists) override
        this to dispatch the reduction on device and prefetch only the
        small result — on TPU the device→host link, not compute, bounds
        streaming FPS."""
        for m in buf.memories:
            m.prefetch()
        return buf

    def complete(self, token: Any, config: TensorsConfig) -> Buffer:
        """Turn a ``submit`` token into the decoded buffer."""
        return self.decode(token, config)

    def token_ready(self, token: Any) -> bool:
        """Non-blocking: True when ``complete(token)`` would not stall on a
        device→host transfer. Walks the token's TensorMemory/Buffer members
        (tuples of them are the submit-token convention). The decoder
        element drains ready frames eagerly and only blocks when the
        pipeline exceeds ``async_depth`` — on TPU the readback RTT is far
        larger than per-frame host work, so depth alone can't hide it."""
        return _ready(token)

    # -- epilogue fusion (ops/epilogue.py) ----------------------------------- #
    #: set by the epilogue fuser: the upstream filter's jit already ran
    #: ``epilogue_reduce`` — buffers arrive carrying the reduced tensor
    _fused_epilogue = False

    def epilogue_reduce(self) -> Optional[Any]:
        """A jax-traceable ``fn(model_output_tuple) -> reduced array`` the
        epilogue fuser compiles INTO the upstream filter's XLA program, or
        None when this decoder has no device reduction. When fused,
        ``decode``/``submit`` receive buffers whose single memory holds the
        reduce result (``_fused_epilogue`` is set by the fuser) and must be
        bit-identical to the unfused path."""
        return None

    def fusion_signature(self) -> str:
        """Structural identity of the fused reduce for the sched
        coalesce token: same mode+options ⇒ same reduce function."""
        opts = ",".join(f"{k}={self.options.get(k)}"
                        for k in sorted(self.options))
        return f"{self.MODE}:{opts}"


def _ready(obj: Any) -> bool:
    if isinstance(obj, TensorMemory):
        return obj.is_ready()
    if isinstance(obj, Buffer):
        return all(m.is_ready() for m in obj.memories)
    if isinstance(obj, (tuple, list)):
        return all(_ready(v) for v in obj)
    return True


def register_decoder(cls: type) -> type:
    register_subplugin(SubpluginType.DECODER, cls.MODE, cls, replace=True)
    for alias in getattr(cls, "ALIASES", ()):
        register_subplugin(SubpluginType.DECODER, alias, cls, replace=True)
    return cls


def find_decoder(mode: str) -> Optional[type]:
    from . import _ensure_builtin_decoders

    _ensure_builtin_decoders()
    return get_subplugin(SubpluginType.DECODER, mode)
