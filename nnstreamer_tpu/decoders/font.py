"""font decoder — renders tensor values as text onto a video frame.

Reference: ext/nnstreamer/tensor_decoder/tensordec-font.c (renders the
tensor's textual content with a sprite font). option1 = "W:H" output size.
Input: uint8 tensor holding UTF-8 bytes (e.g. image_labeling output) or any
numeric tensor (rendered as formatted numbers).
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorDType, TensorsConfig
from .base import Decoder, register_decoder
from .util import draw_text, new_canvas


@register_decoder
class FontDecoder(Decoder):
    MODE = "font"

    def init(self, options) -> None:
        super().init(options)
        w, h = (self.option(1, "256:64")).split(":")
        self.out_w, self.out_h = int(w), int(h)

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("video/x-raw", {"format": "RGBA", "width": self.out_w,
                                    "height": self.out_h,
                                    "framerate": config.rate})

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        arr = buf.memories[0].host()
        if arr.dtype == np.uint8:
            text = arr.tobytes().split(b"\x00")[0].decode("utf-8", "replace")
        else:
            vals = np.asarray(arr).reshape(-1)[:8]
            text = " ".join(f"{v:.3g}" for v in vals)
        canvas = new_canvas(self.out_w, self.out_h)
        for i, line in enumerate(text.split("\n")):
            draw_text(canvas, 2, 2 + i * 9, line)
        out = buf.with_memories([TensorMemory(canvas)])
        out.meta["text"] = text
        return out
