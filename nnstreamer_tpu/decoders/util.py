"""Decoder helpers: label files, drawing primitives, NMS.

Reference: ext/nnstreamer/tensor_decoder/tensordecutil.c (label-file load,
sprite font) — drawing here is plain numpy rasterization onto RGBA canvases,
plus a 5x7 bitmap font for label text.
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence, Tuple

import numpy as np


def load_labels(path: str) -> List[str]:
    """One label per line (tensordecutil.c _load_label_file)."""
    if not path or not os.path.isfile(path):
        raise FileNotFoundError(f"label file not found: {path}")
    with open(path, "r", encoding="utf-8") as f:
        return [ln.strip() for ln in f if ln.strip()]


# --------------------------------------------------------------------------- #
# RGBA drawing (tensordec-boundingbox.c draws boxes+label sprites on a
# transparent canvas; same contract here)
# --------------------------------------------------------------------------- #

def new_canvas(width: int, height: int) -> np.ndarray:
    return np.zeros((height, width, 4), np.uint8)


def draw_rect(canvas: np.ndarray, x0: int, y0: int, x1: int, y1: int,
              color: Sequence[int] = (0, 255, 0, 255), thickness: int = 1) -> None:
    h, w = canvas.shape[:2]
    x0, x1 = sorted((int(np.clip(x0, 0, w - 1)), int(np.clip(x1, 0, w - 1))))
    y0, y1 = sorted((int(np.clip(y0, 0, h - 1)), int(np.clip(y1, 0, h - 1))))
    c = np.asarray(color, np.uint8)
    for t in range(thickness):
        xa, ya, xb, yb = x0 + t, y0 + t, x1 - t, y1 - t
        if xa > xb or ya > yb:
            break
        canvas[ya, xa:xb + 1] = c
        canvas[yb, xa:xb + 1] = c
        canvas[ya:yb + 1, xa] = c
        canvas[ya:yb + 1, xb] = c


def draw_disc(canvas: np.ndarray, cx: int, cy: int, radius: int,
              color: Sequence[int] = (255, 0, 0, 255)) -> None:
    # rasterize only the disc's bounding square — a full-canvas mask is
    # O(H*W) per call and dominated the pose decoder's per-frame cost
    h, w = canvas.shape[:2]
    x0, x1 = max(cx - radius, 0), min(cx + radius + 1, w)
    y0, y1 = max(cy - radius, 0), min(cy + radius + 1, h)
    if x0 >= x1 or y0 >= y1:
        return
    y, x = np.ogrid[y0:y1, x0:x1]
    mask = (x - cx) ** 2 + (y - cy) ** 2 <= radius ** 2
    canvas[y0:y1, x0:x1][mask] = np.asarray(color, np.uint8)


def draw_line(canvas: np.ndarray, x0: int, y0: int, x1: int, y1: int,
              color: Sequence[int] = (255, 255, 0, 255)) -> None:
    n = int(max(abs(x1 - x0), abs(y1 - y0), 1))
    xs = np.linspace(x0, x1, n + 1).round().astype(int)
    ys = np.linspace(y0, y1, n + 1).round().astype(int)
    h, w = canvas.shape[:2]
    ok = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    canvas[ys[ok], xs[ok]] = np.asarray(color, np.uint8)


# 5x7 font for label text (subset; tensordecutil sprite equivalent)
_FONT: Dict[str, Tuple[int, ...]] = {}


def _deffont(ch: str, rows: Sequence[str]) -> None:
    _FONT[ch] = tuple(int(r.replace(".", "0").replace("#", "1"), 2) for r in rows)


for ch, rows in {
    "0": ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    "1": ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    "2": ["01110", "10001", "00001", "00110", "01000", "10000", "11111"],
    "3": ["01110", "10001", "00001", "00110", "00001", "10001", "01110"],
    "4": ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    "5": ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    "6": ["01110", "10000", "11110", "10001", "10001", "10001", "01110"],
    "7": ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    "8": ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    "9": ["01110", "10001", "10001", "01111", "00001", "00001", "01110"],
}.items():
    _deffont(ch, rows)

_ALPHA = {
    "a": ["01110", "00001", "01111", "10001", "01111"],
    "b": ["10000", "10000", "11110", "10001", "11110"],
    "c": ["01110", "10000", "10000", "10000", "01110"],
    "d": ["00001", "00001", "01111", "10001", "01111"],
    "e": ["01110", "10001", "11111", "10000", "01110"],
    "f": ["00110", "01000", "11100", "01000", "01000"],
    "g": ["01111", "10001", "01111", "00001", "01110"],
    "h": ["10000", "10000", "11110", "10001", "10001"],
    "i": ["00100", "00000", "00100", "00100", "00100"],
    "j": ["00010", "00000", "00010", "10010", "01100"],
    "k": ["10000", "10010", "11100", "10010", "10001"],
    "l": ["01100", "00100", "00100", "00100", "01110"],
    "m": ["00000", "11010", "10101", "10101", "10101"],
    "n": ["00000", "11110", "10001", "10001", "10001"],
    "o": ["01110", "10001", "10001", "10001", "01110"],
    "p": ["11110", "10001", "11110", "10000", "10000"],
    "q": ["01111", "10001", "01111", "00001", "00001"],
    "r": ["00000", "10110", "11000", "10000", "10000"],
    "s": ["01111", "10000", "01110", "00001", "11110"],
    "t": ["01000", "11100", "01000", "01000", "00110"],
    "u": ["00000", "10001", "10001", "10011", "01101"],
    "v": ["00000", "10001", "10001", "01010", "00100"],
    "w": ["00000", "10101", "10101", "10101", "01010"],
    "x": ["00000", "10001", "01110", "01110", "10001"],
    "y": ["10001", "10001", "01111", "00001", "01110"],
    "z": ["11111", "00010", "00100", "01000", "11111"],
}
for ch, rows in _ALPHA.items():
    _deffont(ch, ["00000", "00000"] + rows if len(rows) == 5 else rows)


#: rendered-text sprite cache: text → mask (7,W) bool (color-independent;
#: the color applies at blit time). Rendering glyph bitmaps per character
#: per frame is Python-loop-bound; labels repeat across frames, so each
#: unique string rasterizes once and then blits.
_SPRITES: Dict[str, np.ndarray] = {}


def _text_mask(text: str) -> np.ndarray:
    mask = np.zeros((7, 6 * len(text)), bool)
    for i, ch in enumerate(text.lower()):
        glyph = _FONT.get(ch)
        if glyph is None:
            continue
        for ry, rowbits in enumerate(glyph):
            for rx in range(5):
                if rowbits & (1 << (4 - rx)):
                    mask[ry, i * 6 + rx] = True
    return mask


def draw_text(canvas: np.ndarray, x: int, y: int, text: str,
              color: Sequence[int] = (255, 255, 255, 255)) -> None:
    if not text:
        return
    mask = _SPRITES.get(text)
    if mask is None:
        if len(_SPRITES) > 4096:  # unbounded label sets stay bounded
            _SPRITES.clear()
        mask = _SPRITES[text] = _text_mask(text)
    h, w = canvas.shape[:2]
    mh, mw = mask.shape
    x0, y0 = max(x, 0), max(y, 0)
    x1, y1 = min(x + mw, w), min(y + mh, h)
    if x0 >= x1 or y0 >= y1:
        return
    sub = mask[y0 - y:y1 - y, x0 - x:x1 - x]
    canvas[y0:y1, x0:x1][sub] = np.asarray(color, np.uint8)


# --------------------------------------------------------------------------- #
# Non-maximum suppression (tensordec-boundingbox.c nms, iou threshold 0.5)
# --------------------------------------------------------------------------- #

def iou(a: np.ndarray, b: np.ndarray) -> float:
    ax0, ay0, ax1, ay1 = a[:4]
    bx0, by0, bx1, by1 = b[:4]
    ix = max(0.0, min(ax1, bx1) - max(ax0, bx0))
    iy = max(0.0, min(ay1, by1) - max(ay0, by0))
    inter = ix * iy
    ua = (ax1 - ax0) * (ay1 - ay0) + (bx1 - bx0) * (by1 - by0) - inter
    return inter / ua if ua > 0 else 0.0


def nms(boxes: np.ndarray, iou_threshold: float = 0.5) -> np.ndarray:
    """boxes: (N, >=5) rows [x0,y0,x1,y1,score,...]; returns kept rows,
    score-descending (reference do_nms, greedy same-order semantics), with
    the pairwise IOU row vectorized — the reference's O(N²) scalar loop is
    seconds per frame at SSD anchor counts."""
    if len(boxes) == 0:
        return boxes
    order = np.argsort(-boxes[:, 4], kind="stable")
    boxes = boxes[order]
    x0, y0, x1, y1 = (boxes[:, i].astype(np.float64) for i in range(4))
    areas = (x1 - x0) * (y1 - y0)
    alive = np.ones(len(boxes), bool)
    keep: List[int] = []
    for i in range(len(boxes)):
        if not alive[i]:
            continue
        keep.append(i)
        rest = alive.copy()
        rest[: i + 1] = False
        if not rest.any():
            continue
        ix = np.minimum(x1[i], x1[rest]) - np.maximum(x0[i], x0[rest])
        iy = np.minimum(y1[i], y1[rest]) - np.maximum(y0[i], y0[rest])
        inter = np.clip(ix, 0, None) * np.clip(iy, 0, None)
        union = areas[i] + areas[rest] - inter
        over = np.where(union > 0, inter / union, 0.0) > iou_threshold
        alive[np.flatnonzero(rest)[over]] = False
    return boxes[keep]
