"""Decoder subplugins (tensor → media)."""

from .base import Decoder, find_decoder, register_decoder

_loaded = False


def _ensure_builtin_decoders() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import basic  # noqa: F401
    from . import bounding_box  # noqa: F401
    from . import image_segment  # noqa: F401
    from . import pose  # noqa: F401
    from . import font  # noqa: F401
    from ..converters import protobuf_io  # noqa: F401
    try:
        from ..converters import fb_io  # noqa: F401
    except ImportError:
        # flatbuffers runtime absent: register stubs so mode=flexbuf/flatbuf
        # fails with the actionable cause, not "unknown mode"
        from ..converters import register_converter

        class _MissingFlatbuffers(Decoder):
            MODE = "flexbuf"
            ALIASES = ("flatbuf",)

            def init(self, options) -> None:
                raise ImportError(
                    "mode=flexbuf/flatbuf needs the 'flatbuffers' package "
                    "(pip install flatbuffers); the dependency-free native "
                    "framing is available as mode=flex")

        register_decoder(_MissingFlatbuffers)

        def _missing(buf, props):
            raise ImportError(
                "converter mode=flexbuf/flatbuf needs the 'flatbuffers' "
                "package (pip install flatbuffers)")

        register_converter("flexbuf", _missing)
        register_converter("flatbuf", _missing)


_ensure_builtin_decoders()

__all__ = ["Decoder", "find_decoder", "register_decoder"]
