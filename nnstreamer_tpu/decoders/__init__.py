"""Decoder subplugins (tensor → media)."""

from .base import Decoder, find_decoder, register_decoder

_loaded = False


def _ensure_builtin_decoders() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import basic  # noqa: F401
    from . import bounding_box  # noqa: F401
    from . import image_segment  # noqa: F401
    from . import pose  # noqa: F401
    from . import font  # noqa: F401
    from ..converters import protobuf_io  # noqa: F401
    try:
        from ..converters import fb_io  # noqa: F401
    except ImportError:  # flatbuffers runtime not installed
        pass


_ensure_builtin_decoders()

__all__ = ["Decoder", "find_decoder", "register_decoder"]
