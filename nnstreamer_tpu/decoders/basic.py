"""Basic decoders: direct_video, image_labeling, flexbuf.

References: tensordec-directvideo.c, tensordec-imagelabel.c,
tensordec-flexbuf.cc.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.meta import wrap_flex
from ..core.types import Caps, TensorsConfig
from .base import Decoder, register_decoder
from .util import load_labels


@register_decoder
class DirectVideo(Decoder):
    """tensor [C:W:H:1] (C∈{1,3,4}) → video/x-raw frame (passthrough view)."""

    MODE = "direct_video"

    _FMT = {1: "GRAY8", 3: "RGB", 4: "RGBA"}

    def out_caps(self, config: TensorsConfig) -> Caps:
        shape = config.info[0].shape  # (N,H,W,C)
        if len(shape) != 4 or shape[-1] not in self._FMT:
            raise ValueError(f"direct_video: bad tensor shape {shape}")
        return Caps("video/x-raw", {"format": self._FMT[shape[-1]],
                                    "width": shape[2], "height": shape[1],
                                    "framerate": config.rate})

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        arr = buf.memories[0].host()
        if arr.ndim == 4:
            arr = arr[0]
        return buf.with_memories([TensorMemory(np.ascontiguousarray(arr, np.uint8))])


@register_decoder
class ImageLabeling(Decoder):
    """scores tensor → text/x-raw best label (tensordec-imagelabel.c):
    option1 = label file."""

    MODE = "image_labeling"

    def init(self, options) -> None:
        super().init(options)
        self.labels = load_labels(self.option(1))

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("text/x-raw", {"format": "utf8"})

    @staticmethod
    def _rows(arr):
        """Scores as (frames, classes): a batched tensor (converter
        frames-per-tensor regrouping) yields one label per frame."""
        return arr.reshape(-1) if arr.ndim <= 1 or arr.shape[0] == 1 \
            else arr.reshape(arr.shape[0], -1)

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        m = buf.memories[0]
        if m.is_device and not m.prefetched:
            # argmax on device: D2H transfers 2 scalars per frame, not the
            # logits
            import jax
            import jax.numpy as jnp

            if not hasattr(self, "_argmax"):
                # one stacked fetch: each D2H readback pays full RTT, so
                # (argmax, max) come back as a single array
                self._argmax = jax.jit(
                    lambda x: jnp.stack(
                        [jnp.argmax(self._rows(x), axis=-1)
                         .astype(jnp.float32).reshape(-1),
                         jnp.max(self._rows(x), axis=-1)
                         .astype(jnp.float32).reshape(-1)], axis=1))
            pairs = np.asarray(self._argmax(m.device()))
        else:
            rows = np.atleast_2d(self._rows(m.host()))
            idxs = np.argmax(rows, axis=-1)
            pairs = np.stack(
                [idxs.astype(np.float32),
                 rows[np.arange(len(rows)), idxs].astype(np.float32)], axis=1)
        names = [self.labels[int(i)] if int(i) < len(self.labels) else str(int(i))
                 for i, _ in pairs]
        label, idx, top = names[0], int(pairs[0][0]), float(pairs[0][1])
        out = buf.with_memories(
            [TensorMemory(np.frombuffer("\n".join(names).encode("utf-8"),
                                        np.uint8).copy())])
        out.meta.update(label=label, label_index=idx, label_score=top)
        if len(names) > 1:
            out.meta.update(labels=names,
                            label_indices=[int(i) for i, _ in pairs],
                            label_scores=[float(s) for _, s in pairs])
        return out


@register_decoder
class FlexBuf(Decoder):
    """tensors → self-describing flex blobs using our native 128-byte meta
    header wire format (the query/edge links' framing). For reference-style
    FlexBuffers/FlatBuffers interop blobs use mode=flexbuf / mode=flatbuf
    (converters/fb_io.py)."""

    MODE = "flex"

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("application/octet-stream")

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        blobs = [np.frombuffer(wrap_flex(m.tobytes(), m.info), np.uint8).copy()
                 for m in buf.memories]
        return buf.with_memories([TensorMemory(b) for b in blobs])
