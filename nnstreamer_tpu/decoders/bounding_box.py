"""bounding_box decoder — SSD-style detection → RGBA overlay video.

Reference: ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c (modes
:121-133; scales/thresholds :40-58). Supported modes (option1):

  * ``mobilenet-ssd``            — raw SSD head: locations [4:N:1] + class
    logits [L:N:1]; needs a box-priors file (option3), sigmoid scoring,
    center-size decode with scales (Y,X,H,W)=(10,10,5,5), NMS@0.5.
  * ``mobilenet-ssd-postprocess``— model already decoded: boxes [4:M],
    class ids [M], scores [M], count [1] (tflite detection postprocess).
  * ``ov-person-detection`` / ``ov-face-detection`` — OpenVINO layout
    rows [image_id, label, conf, x0, y0, x1, y1].
  * ``tflite-ssd`` / ``tf-ssd`` — backward-compat OLDNAME aliases for the
    first two modes (tensordec-boundingbox.c:129-131, 151-159).

Options: option2=label file, option3=priors file[:threshold[:iou]],
option4="W:H" output video size, option5="W:H" model input size.
Output: transparent RGBA canvas with green boxes + white label text
(compose over the source video downstream), identical contract to the
reference decoder.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorsConfig
from ..obs import profile as _profile
from .base import Decoder, register_decoder
from .util import draw_rect, draw_text, load_labels, new_canvas, nms

# center-size decode scales (tensordec-boundingbox.c:40-47)
Y_SCALE, X_SCALE, H_SCALE, W_SCALE = 10.0, 10.0, 5.0, 5.0
DEFAULT_THRESHOLD = 0.5
DEFAULT_IOU = 0.5


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def ssd_box_math(xp, locs, raw_scores, priors):
    """Center-size decode + sigmoid class scores, array-namespace-agnostic
    (xp = numpy for the host path, jax.numpy inside the device-reduce jit —
    ONE implementation so the two paths cannot diverge).
    Returns (x0, y0, x1, y1, cls_scores) with cls_scores (N, L-1),
    background class 0 already dropped."""
    locs = locs.reshape(-1, 4).astype(xp.float32)
    scores = 1.0 / (1.0 + xp.exp(
        -raw_scores.reshape(locs.shape[0], -1).astype(xp.float32)))
    ycenter = locs[:, 0] / Y_SCALE * priors[2] + priors[0]
    xcenter = locs[:, 1] / X_SCALE * priors[3] + priors[1]
    hh = xp.exp(locs[:, 2] / H_SCALE) * priors[2]
    ww = xp.exp(locs[:, 3] / W_SCALE) * priors[3]
    return (xcenter - ww / 2, ycenter - hh / 2,
            xcenter + ww / 2, ycenter + hh / 2, scores[:, 1:])


def load_box_priors(path: str) -> np.ndarray:
    """Priors file: 4 whitespace-separated float rows [ycenter,xcenter,h,w]
    (reference box_priors.txt layout)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"box priors file not found: {path}")
    rows: List[List[float]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            vals = [float(v) for v in line.split()]
            if vals:
                rows.append(vals)
    if len(rows) < 4:
        raise ValueError(f"box priors file needs 4 rows, got {len(rows)}")
    return np.asarray(rows[:4], np.float32)  # (4, N)


@register_decoder
class BoundingBox(Decoder):
    MODE = "bounding_box"
    ALIASES = ("boundingbox",)

    def init(self, options) -> None:
        super().init(options)
        self.box_mode = self.option(1, "mobilenet-ssd").lower()
        label_path = self.option(2)
        self.labels = load_labels(label_path) if label_path else []
        self.threshold = DEFAULT_THRESHOLD
        self.iou_threshold = DEFAULT_IOU
        self.priors: Optional[np.ndarray] = None
        opt3 = self.option(3)
        if opt3:
            parts = opt3.split(":")
            if self.box_mode in ("mobilenet-ssd", "tflite-ssd"):
                self.priors = load_box_priors(parts[0])
                extra = parts[1:]
            else:
                extra = parts
            if len(extra) >= 1 and extra[0]:
                self.threshold = float(extra[0])
            if len(extra) >= 2 and extra[1]:
                self.iou_threshold = float(extra[1])
        self.out_w, self.out_h = _parse_wh(self.option(4, "640:480"))
        self.in_w, self.in_h = _parse_wh(self.option(5, "300:300"))

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("video/x-raw", {"format": "RGBA", "width": self.out_w,
                                    "height": self.out_h,
                                    "framerate": config.rate})

    # -- decode modes -------------------------------------------------------- #
    def _objects_mobilenet_ssd(self, buf: Buffer) -> np.ndarray:
        if self.priors is None:
            raise ValueError("mobilenet-ssd mode requires option3 box-priors file")
        x0, y0, x1, y1, cls = ssd_box_math(
            np, buf.memories[0].host(), buf.memories[1].host(), self.priors)
        best = np.argmax(cls, axis=1)
        best_score = cls[np.arange(len(best)), best]
        sel = np.nonzero(best_score >= self.threshold)[0]
        if len(sel) > self.PRE_NMS_TOPK:
            order = np.argsort(-best_score[sel], kind="stable")[:self.PRE_NMS_TOPK]
            sel = np.sort(sel[order])
        return np.stack(
            [x0[sel], y0[sel], x1[sel], y1[sel], best_score[sel],
             (best[sel] + 1).astype(np.float32)], axis=1) if len(sel) else \
            np.zeros((0, 6), np.float32)

    def _objects_postprocess(self, buf: Buffer) -> np.ndarray:
        boxes = buf.memories[0].host().reshape(-1, 4).astype(np.float32)
        classes = buf.memories[1].host().reshape(-1).astype(np.float32)
        scores = buf.memories[2].host().reshape(-1).astype(np.float32)
        n = int(buf.memories[3].host().reshape(-1)[0]) if buf.num_tensors > 3 \
            else len(scores)
        out = []
        for i in range(min(n, len(scores))):
            if scores[i] < self.threshold:
                continue
            ymin, xmin, ymax, xmax = boxes[i]
            out.append([xmin, ymin, xmax, ymax, scores[i], classes[i]])
        return np.asarray(out, np.float32).reshape(-1, 6)

    def _objects_ov(self, buf: Buffer) -> np.ndarray:
        rows = buf.memories[0].host().reshape(-1, 7).astype(np.float32)
        out = []
        for r in rows:
            if r[0] < 0 or r[2] < self.threshold:
                continue
            out.append([r[3], r[4], r[5], r[6], r[2], r[1]])
        return np.asarray(out, np.float32).reshape(-1, 6)

    #: pre-NMS candidate cap, applied identically on the host and device
    #: paths: the top-K anchors by best-class score enter NMS (the tflite
    #: detection-postprocess convention the reference consumes via its
    #: mobilenet-ssd-postprocess mode). A static K is what lets the whole
    #: threshold→top-K→NMS reduction compile to one fixed-shape XLA program:
    #: D2H ships K rows of 6 floats instead of N_anchors×(4+num_classes)
    #: logits, and no data-dependent host fallback exists to serialize the
    #: stream (submit/complete stays fully pipelined).
    PRE_NMS_TOPK = 256

    def _make_reduce(self):
        """``(jax reduce fn, arity)`` for this mode's device reduction
        (arity = leading memories consumed; None = all), or None.

        Every mode funnels into one shape: rank candidates (threshold
        mask → ``top_k``, score -1 ⇒ unused slot), then the greedy NMS
        sweep (ops.pallas.epilogue.nms_sweep — reference nms(),
        tensordec-boundingbox.c:962-976: strict > suppresses), emitting
        fixed (K, 6) rows [x0, y0, x1, y1, score, class]. The same jit
        serves the async submit path and ``epilogue_reduce``."""
        import jax
        import jax.numpy as jnp

        from ..ops.pallas import epilogue as _ep

        threshold = float(self.threshold)
        iou_thr = float(self.iou_threshold)
        topk = self.PRE_NMS_TOPK

        def nms_rows(bx0, by0, bx1, by1, top_score, cls_sel):
            out_score = _ep.nms_sweep(
                bx0, by0, bx1, by1, top_score,
                iou_threshold=iou_thr, threshold=threshold)
            return jnp.stack([bx0, by0, bx1, by1, out_score, cls_sel],
                             axis=1)

        if self.box_mode in ("mobilenet-ssd", "tflite-ssd"):
            if self.priors is None:
                return None
            pr = jnp.asarray(self.priors, jnp.float32)

            def reduce_ssd(locs, raw):
                x0, y0, x1, y1, cls = ssd_box_math(jnp, locs, raw, pr)
                best_score, best = _ep.class_reduce(cls)
                k = min(topk, int(best_score.shape[0]))
                # mask below-threshold anchors out before ranking so the
                # K slots hold only real candidates (score -1 ⇒ unused)
                masked = jnp.where(best_score >= threshold,
                                   best_score, -1.0)
                top_score, idx = jax.lax.top_k(masked, k)
                return nms_rows(x0[idx], y0[idx], x1[idx], y1[idx],
                                top_score,
                                (best[idx] + 1).astype(jnp.float32))

            return reduce_ssd, 2
        if self.box_mode in ("mobilenet-ssd-postprocess", "tf-ssd",
                             "tflite-ssd-postprocess"):
            def reduce_post(boxes, classes, scores, *rest):
                boxes = boxes.reshape(-1, 4).astype(jnp.float32)
                classes = classes.reshape(-1).astype(jnp.float32)
                scores = scores.reshape(-1).astype(jnp.float32)
                m = int(scores.shape[0])
                if rest:  # count tensor caps valid rows (input order)
                    count = jnp.minimum(
                        rest[0].reshape(-1)[0].astype(jnp.int32), m)
                    valid = jnp.arange(m) < count
                else:
                    valid = jnp.ones((m,), bool)
                masked = jnp.where(valid & (scores >= threshold),
                                   scores, -1.0)
                top_score, idx = jax.lax.top_k(masked, min(topk, m))
                b = boxes[idx]  # rows are [ymin, xmin, ymax, xmax]
                return nms_rows(b[:, 1], b[:, 0], b[:, 3], b[:, 2],
                                top_score, classes[idx])

            return reduce_post, None
        if self.box_mode.startswith("ov-"):
            def reduce_ov(rows):
                r = rows.reshape(-1, 7).astype(jnp.float32)
                masked = jnp.where(
                    (r[:, 0] >= 0) & (r[:, 2] >= threshold), r[:, 2], -1.0)
                top_score, idx = jax.lax.top_k(
                    masked, min(topk, int(r.shape[0])))
                rr = r[idx]
                return nms_rows(rr[:, 3], rr[:, 4], rr[:, 5], rr[:, 6],
                                top_score, rr[:, 1])

            return reduce_ov, 1
        return None

    def epilogue_reduce(self):
        made = self._make_reduce()
        if made is None:
            return None
        reduce, arity = made

        def fn(outs):
            return reduce(*(outs if arity is None else outs[:arity]))

        return fn

    def _device_reduce_for(self, buf: Buffer):
        """(jitted reduce, memories) when every consumed memory is already
        device-resident — host tensors decode on host for free instead."""
        if not hasattr(self, "_device_reduce"):
            import jax

            made = self._make_reduce()
            self._device_reduce = None if made is None \
                else (jax.jit(made[0]), made[1])
        dr = self._device_reduce
        if dr is None:
            return None
        fn, arity = dr
        if arity is not None and buf.num_tensors < arity:
            return None
        mems = buf.memories if arity is None else buf.memories[:arity]
        if not mems or not all(m.is_device for m in mems):
            return None
        return fn, mems

    def submit(self, buf: Buffer, config: TensorsConfig):
        if self._fused_epilogue:
            # the upstream filter's jit already ran the fused reduce:
            # memories[0] holds the (K, 6) rows — keep the D2H in flight
            mem = buf.memories[0]
            mem.prefetch()
            return (buf, mem)
        red = self._device_reduce_for(buf)
        if red is not None:
            # box decode + class max + threshold + top-K + greedy NMS, all
            # on device in one jit — complete() only filters kept rows
            fn, mems = red
            arrays = [m.device() for m in mems]
            prof = _profile.DISPATCH_HOOK
            out = prof.dispatch_fn(f"decode:{self.box_mode}", fn, *arrays) \
                if prof is not None else fn(*arrays)
            rows = TensorMemory(out)
            rows.prefetch()
            return (buf, rows)
        return super().submit(buf, config)

    def complete(self, token, config: TensorsConfig) -> Buffer:
        if isinstance(token, tuple):
            buf, rows_mem = token
            rows = rows_mem.host()
            # device reduce already thresholded + NMS'd (suppressed slots
            # carry score -1); don't pay the O(K²) host NMS again
            objs = rows[rows[:, 4] >= self.threshold]
            return self._finish(objs, buf, suppressed=True)
        return self.decode(token, config)

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        if self._fused_epilogue:
            rows = np.asarray(buf.memories[0].host())
            objs = rows[rows[:, 4] >= self.threshold]
            return self._finish(objs, buf, suppressed=True)
        if self.box_mode in ("mobilenet-ssd", "tflite-ssd"):
            objs = self._objects_mobilenet_ssd(buf)
        elif self.box_mode in ("mobilenet-ssd-postprocess", "tf-ssd",
                               "tflite-ssd-postprocess"):
            objs = self._objects_postprocess(buf)
        elif self.box_mode.startswith("ov-"):
            objs = self._objects_ov(buf)
        else:
            raise ValueError(f"bounding_box: unknown mode {self.box_mode!r}")
        return self._finish(objs, buf)

    def _finish(self, objs: np.ndarray, buf: Buffer,
                suppressed: bool = False) -> Buffer:
        if not suppressed:
            objs = nms(objs, self.iou_threshold)
        canvas = new_canvas(self.out_w, self.out_h)
        detections = []
        for x0, y0, x1, y1, score, cls in objs:
            px0, py0 = int(x0 * self.out_w), int(y0 * self.out_h)
            px1, py1 = int(x1 * self.out_w), int(y1 * self.out_h)
            draw_rect(canvas, px0, py0, px1, py1)
            cls_i = int(cls)
            label = self.labels[cls_i] if cls_i < len(self.labels) else str(cls_i)
            draw_text(canvas, px0 + 2, py0 + 2, label)
            detections.append({"box": (float(x0), float(y0), float(x1), float(y1)),
                               "score": float(score), "class": cls_i,
                               "label": label})
        out = buf.with_memories([TensorMemory(canvas)])
        out.meta["detections"] = detections
        return out


def _parse_wh(s: str) -> Tuple[int, int]:
    w, h = s.split(":")
    return int(w), int(h)
