"""bounding_box decoder — SSD-style detection → RGBA overlay video.

Reference: ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c (modes
:121-133; scales/thresholds :40-58). Supported modes (option1):

  * ``mobilenet-ssd``            — raw SSD head: locations [4:N:1] + class
    logits [L:N:1]; needs a box-priors file (option3), sigmoid scoring,
    center-size decode with scales (Y,X,H,W)=(10,10,5,5), NMS@0.5.
  * ``mobilenet-ssd-postprocess``— model already decoded: boxes [4:M],
    class ids [M], scores [M], count [1] (tflite detection postprocess).
  * ``ov-person-detection`` / ``ov-face-detection`` — OpenVINO layout
    rows [image_id, label, conf, x0, y0, x1, y1].
  * ``tflite-ssd`` / ``tf-ssd`` — backward-compat OLDNAME aliases for the
    first two modes (tensordec-boundingbox.c:129-131, 151-159).

Options: option2=label file, option3=priors file[:threshold[:iou]],
option4="W:H" output video size, option5="W:H" model input size.
Output: transparent RGBA canvas with green boxes + white label text
(compose over the source video downstream), identical contract to the
reference decoder.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorsConfig
from .base import Decoder, register_decoder
from .util import draw_rect, draw_text, load_labels, new_canvas, nms

# center-size decode scales (tensordec-boundingbox.c:40-47)
Y_SCALE, X_SCALE, H_SCALE, W_SCALE = 10.0, 10.0, 5.0, 5.0
DEFAULT_THRESHOLD = 0.5
DEFAULT_IOU = 0.5


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def load_box_priors(path: str) -> np.ndarray:
    """Priors file: 4 whitespace-separated float rows [ycenter,xcenter,h,w]
    (reference box_priors.txt layout)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"box priors file not found: {path}")
    rows: List[List[float]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            vals = [float(v) for v in line.split()]
            if vals:
                rows.append(vals)
    if len(rows) < 4:
        raise ValueError(f"box priors file needs 4 rows, got {len(rows)}")
    return np.asarray(rows[:4], np.float32)  # (4, N)


@register_decoder
class BoundingBox(Decoder):
    MODE = "bounding_box"
    ALIASES = ("boundingbox",)

    def init(self, options) -> None:
        super().init(options)
        self.box_mode = self.option(1, "mobilenet-ssd").lower()
        label_path = self.option(2)
        self.labels = load_labels(label_path) if label_path else []
        self.threshold = DEFAULT_THRESHOLD
        self.iou_threshold = DEFAULT_IOU
        self.priors: Optional[np.ndarray] = None
        opt3 = self.option(3)
        if opt3:
            parts = opt3.split(":")
            if self.box_mode in ("mobilenet-ssd", "tflite-ssd"):
                self.priors = load_box_priors(parts[0])
                extra = parts[1:]
            else:
                extra = parts
            if len(extra) >= 1 and extra[0]:
                self.threshold = float(extra[0])
            if len(extra) >= 2 and extra[1]:
                self.iou_threshold = float(extra[1])
        self.out_w, self.out_h = _parse_wh(self.option(4, "640:480"))
        self.in_w, self.in_h = _parse_wh(self.option(5, "300:300"))

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("video/x-raw", {"format": "RGBA", "width": self.out_w,
                                    "height": self.out_h,
                                    "framerate": config.rate})

    # -- decode modes -------------------------------------------------------- #
    def _objects_mobilenet_ssd(self, buf: Buffer) -> np.ndarray:
        if self.priors is None:
            raise ValueError("mobilenet-ssd mode requires option3 box-priors file")
        locs = buf.memories[0].host().reshape(-1, 4).astype(np.float32)   # (N,4)
        raw = buf.memories[1].host()
        scores = _sigmoid(raw.reshape(-1, raw.shape[-1] if raw.ndim > 1 else
                                      raw.size // locs.shape[0]).astype(np.float32))
        scores = scores.reshape(locs.shape[0], -1)                         # (N,L)
        pr = self.priors  # (4,N): ycenter,xcenter,h,w
        ycenter = locs[:, 0] / Y_SCALE * pr[2] + pr[0]
        xcenter = locs[:, 1] / X_SCALE * pr[3] + pr[1]
        hh = np.exp(locs[:, 2] / H_SCALE) * pr[2]
        ww = np.exp(locs[:, 3] / W_SCALE) * pr[3]
        x0, y0 = xcenter - ww / 2, ycenter - hh / 2
        x1, y1 = xcenter + ww / 2, ycenter + hh / 2
        out = []
        cls = scores[:, 1:]  # class 0 = background
        best = np.argmax(cls, axis=1)
        best_score = cls[np.arange(len(best)), best]
        sel = best_score >= self.threshold
        for i in np.nonzero(sel)[0]:
            out.append([x0[i], y0[i], x1[i], y1[i], best_score[i], best[i] + 1])
        return np.asarray(out, np.float32).reshape(-1, 6)

    def _objects_postprocess(self, buf: Buffer) -> np.ndarray:
        boxes = buf.memories[0].host().reshape(-1, 4).astype(np.float32)
        classes = buf.memories[1].host().reshape(-1).astype(np.float32)
        scores = buf.memories[2].host().reshape(-1).astype(np.float32)
        n = int(buf.memories[3].host().reshape(-1)[0]) if buf.num_tensors > 3 \
            else len(scores)
        out = []
        for i in range(min(n, len(scores))):
            if scores[i] < self.threshold:
                continue
            ymin, xmin, ymax, xmax = boxes[i]
            out.append([xmin, ymin, xmax, ymax, scores[i], classes[i]])
        return np.asarray(out, np.float32).reshape(-1, 6)

    def _objects_ov(self, buf: Buffer) -> np.ndarray:
        rows = buf.memories[0].host().reshape(-1, 7).astype(np.float32)
        out = []
        for r in rows:
            if r[0] < 0 or r[2] < self.threshold:
                continue
            out.append([r[3], r[4], r[5], r[6], r[2], r[1]])
        return np.asarray(out, np.float32).reshape(-1, 6)

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        if self.box_mode in ("mobilenet-ssd", "tflite-ssd"):
            objs = self._objects_mobilenet_ssd(buf)
        elif self.box_mode in ("mobilenet-ssd-postprocess", "tf-ssd",
                               "tflite-ssd-postprocess"):
            objs = self._objects_postprocess(buf)
        elif self.box_mode.startswith("ov-"):
            objs = self._objects_ov(buf)
        else:
            raise ValueError(f"bounding_box: unknown mode {self.box_mode!r}")
        objs = nms(objs, self.iou_threshold)
        canvas = new_canvas(self.out_w, self.out_h)
        detections = []
        for x0, y0, x1, y1, score, cls in objs:
            px0, py0 = int(x0 * self.out_w), int(y0 * self.out_h)
            px1, py1 = int(x1 * self.out_w), int(y1 * self.out_h)
            draw_rect(canvas, px0, py0, px1, py1)
            cls_i = int(cls)
            label = self.labels[cls_i] if cls_i < len(self.labels) else str(cls_i)
            draw_text(canvas, px0 + 2, py0 + 2, label)
            detections.append({"box": (float(x0), float(y0), float(x1), float(y1)),
                               "score": float(score), "class": cls_i,
                               "label": label})
        out = buf.with_memories([TensorMemory(canvas)])
        out.meta["detections"] = detections
        return out


def _parse_wh(s: str) -> Tuple[int, int]:
    w, h = s.split(":")
    return int(w), int(h)
