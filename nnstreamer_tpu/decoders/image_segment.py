"""image_segment decoder — per-pixel class masks → RGBA overlay.

Reference: ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c (schemes
:105-126: tflite-deeplab, snpe-deeplab, snpe-depth). option1 = scheme.

tflite-deeplab: input [classes:W:H:1] float → argmax over classes → per-class
color. snpe-deeplab: input already argmaxed [W:H:1]. snpe-depth: depth map
[1:W:H] → grayscale.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorsConfig
from ..obs import profile as _profile
from .base import Decoder, register_decoder

# 21-class PASCAL VOC palette (RGBA), class 0 = background transparent
_PALETTE = np.zeros((256, 4), np.uint8)
for i in range(1, 256):
    c = np.zeros(3, np.uint8)
    cid, shift = i, 7
    while cid:
        c[0] |= ((cid >> 0) & 1) << shift
        c[1] |= ((cid >> 1) & 1) << shift
        c[2] |= ((cid >> 2) & 1) << shift
        cid >>= 3
        shift -= 1
    _PALETTE[i, :3] = c
    _PALETTE[i, 3] = 160


@register_decoder
class ImageSegment(Decoder):
    MODE = "image_segment"

    def init(self, options) -> None:
        super().init(options)
        self.scheme = self.option(1, "tflite-deeplab").lower()

    def _hw(self, config: TensorsConfig):
        shape = config.info[0].shape  # row-major
        if self.scheme == "tflite-deeplab":
            # dims [classes:W:H:1] → shape (1,H,W,classes)
            return shape[-3], shape[-2]
        return shape[-3], shape[-2] if len(shape) >= 3 else shape

    def out_caps(self, config: TensorsConfig) -> Caps:
        h, w = self._hw(config)
        return Caps("video/x-raw", {"format": "RGBA", "width": w, "height": h,
                                    "framerate": config.rate})

    def _colorize_fn(self):
        """jax fn: logits/class-ids → (H, W, 4) RGBA canvas on device
        (ops.pallas.epilogue.segment_colorize), or None for host-only
        schemes (snpe-depth's min/max normalize is data-dependent)."""
        if self.scheme not in ("tflite-deeplab", "snpe-deeplab"):
            return None
        import jax.numpy as jnp

        from ..ops.pallas import epilogue as _ep

        pre_argmaxed = self.scheme == "snpe-deeplab"

        def fn(x):
            if pre_argmaxed:
                x = jnp.squeeze(x)
            elif x.ndim == 4:
                x = x[0]
            return _ep.segment_colorize(x, _PALETTE,
                                        pre_argmaxed=pre_argmaxed)

        return fn

    def epilogue_reduce(self):
        fn = self._colorize_fn()
        return None if fn is None else (lambda outs: fn(outs[0]))

    def submit(self, buf: Buffer, config: TensorsConfig):
        m = buf.memories[0]
        if self._fused_epilogue:
            # upstream filter already ran argmax+colorize: memories[0]
            # holds the RGBA canvas — keep the D2H in flight
            m.prefetch()
            return (buf, m)
        if m.is_device:
            # argmax + palette on device: D2H ships the H*W*4 uint8
            # canvas, not the H*W*classes float logits, and the per-pixel
            # host NumPy gather disappears from the frame loop
            fn = self._colorize_fn()
            if fn is not None:
                import jax

                if not hasattr(self, "_colorize_jit"):
                    self._colorize_jit = jax.jit(fn)
                prof = _profile.DISPATCH_HOOK
                out = prof.dispatch_fn(f"decode:{self.scheme}",
                                       self._colorize_jit, m.device()) \
                    if prof is not None else self._colorize_jit(m.device())
                canvas_mem = TensorMemory(out)
                canvas_mem.prefetch()
                return (buf, canvas_mem)
        return super().submit(buf, config)

    def complete(self, token, config: TensorsConfig) -> Buffer:
        if isinstance(token, tuple):
            buf, mem = token
            canvas = np.asarray(mem.host())
            if canvas.ndim == 4:
                canvas = canvas[0]
            return buf.with_memories([TensorMemory(np.ascontiguousarray(canvas))])
        return self.decode(token, config)

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        arr = buf.memories[0].host()
        if self._fused_epilogue:
            canvas = np.asarray(arr)
            if canvas.ndim == 4:
                canvas = canvas[0]
            return buf.with_memories(
                [TensorMemory(np.ascontiguousarray(canvas))])
        if self.scheme == "tflite-deeplab":
            if arr.ndim == 4:
                arr = arr[0]
            classes = np.argmax(arr, axis=-1).astype(np.uint8)  # (H,W)
            canvas = _PALETTE[classes]
        elif self.scheme == "snpe-deeplab":
            classes = np.squeeze(arr).astype(np.uint8)
            canvas = _PALETTE[classes]
        elif self.scheme == "snpe-depth":
            depth = np.squeeze(arr).astype(np.float32)
            lo, hi = float(depth.min()), float(depth.max())
            g = ((depth - lo) / (hi - lo + 1e-9) * 255).astype(np.uint8)
            canvas = np.stack([g, g, g, np.full_like(g, 255)], axis=-1)
        else:
            raise ValueError(f"image_segment: unknown scheme {self.scheme!r}")
        out = buf.with_memories([TensorMemory(np.ascontiguousarray(canvas))])
        return out
