"""pose_estimation decoder — keypoint heatmaps → skeleton overlay.

Reference: ext/nnstreamer/tensor_decoder/tensordec-pose.c (:93-149).
option1 = "W:H" output size; option2 = "W:H" model input size;
option3 = keypoint label file (optional); option4 = "heatmap-offset" mode
(posenet displacement decode) or default plain-argmax heatmaps.

Input (default mode): heatmaps dims [K:W:H:1] → shape (1,H,W,K); per
keypoint the argmax cell is the joint location, value (sigmoided) the score.
heatmap-offset mode additionally reads offsets [2K:W:H:1] refining each
location (posenet convention).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorsConfig
from .base import Decoder, register_decoder
from .util import draw_disc, draw_line, load_labels

# COCO-ish default skeleton over 17 keypoints (pairs of keypoint indices)
_DEFAULT_EDGES: Tuple[Tuple[int, int], ...] = (
    (0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8), (8, 10),
    (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14), (14, 16))


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


@register_decoder
class PoseEstimation(Decoder):
    MODE = "pose_estimation"
    ALIASES = ("pose",)

    def init(self, options) -> None:
        super().init(options)
        ow, oh = (self.option(1, "640:480")).split(":")
        self.out_w, self.out_h = int(ow), int(oh)
        iw, ih = (self.option(2, "257:257")).split(":")
        self.in_w, self.in_h = int(iw), int(ih)
        label_path = self.option(3)
        self.labels = load_labels(label_path) if label_path else []
        self.offset_mode = self.option(4, "").lower() == "heatmap-offset"
        self.score_threshold = 0.3

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("video/x-raw", {"format": "RGBA", "width": self.out_w,
                                    "height": self.out_h,
                                    "framerate": config.rate})

    def keypoints(self, buf: Buffer) -> List[Tuple[float, float, float]]:
        hm = buf.memories[0].host()
        if hm.ndim == 4:
            hm = hm[0]  # (H,W,K)
        H, W, K = hm.shape
        pts: List[Tuple[float, float, float]] = []
        offsets = None
        if self.offset_mode and buf.num_tensors > 1:
            offsets = buf.memories[1].host()
            if offsets.ndim == 4:
                offsets = offsets[0]  # (H,W,2K)
        for k in range(K):
            flat = int(np.argmax(hm[:, :, k]))
            y, x = divmod(flat, W)
            score = float(_sigmoid(hm[y, x, k]))
            if offsets is not None:
                # posenet: position = cell/(res-1)*stride + offset
                oy = float(offsets[y, x, k])
                ox = float(offsets[y, x, k + K])
                px = (x / max(W - 1, 1)) * self.in_w + ox
                py = (y / max(H - 1, 1)) * self.in_h + oy
            else:
                px = (x + 0.5) / W * self.in_w
                py = (y + 0.5) / H * self.in_h
            pts.append((px / self.in_w, py / self.in_h, score))
        return pts

    def submit(self, buf: Buffer, config: TensorsConfig):
        m = buf.memories[0]
        use_off = self.offset_mode and buf.num_tensors > 1
        if m.is_device and (not use_off or buf.memories[1].is_device):
            # per-keypoint argmax + gather on device: D2H ships K rows of 5
            # floats instead of the H*W*K heatmaps (+offsets)
            import jax
            import jax.numpy as jnp

            key = "_reduce_off" if use_off else "_reduce"
            if not hasattr(self, key):
                def reduce(hm, off):
                    hm = hm.reshape(hm.shape[-3:])
                    H, W, K = hm.shape
                    flat = hm.reshape(H * W, K)
                    idx = jnp.argmax(flat, axis=0)
                    ks = jnp.arange(K)
                    heat = flat[idx, ks]
                    x = (idx % W).astype(jnp.float32)
                    y = (idx // W).astype(jnp.float32)
                    if off is None:
                        oy = ox = jnp.zeros((K,), jnp.float32)
                    else:
                        off_flat = off.reshape(H * W, 2 * K)
                        oy = off_flat[idx, ks]
                        ox = off_flat[idx, ks + K]
                    return jnp.stack([x, y, heat, oy, ox], axis=1)

                setattr(self, key,
                        jax.jit(reduce) if use_off
                        else jax.jit(lambda hm: reduce(hm, None)))
            fn = getattr(self, key)
            rows = TensorMemory(fn(m.device(), buf.memories[1].device())
                                if use_off else fn(m.device()))
            rows.prefetch()
            hm_shape = m.shape[-3:]
            return (buf, rows, hm_shape)
        return super().submit(buf, config)

    def complete(self, token, config: TensorsConfig) -> Buffer:
        if isinstance(token, tuple):
            buf, rows_mem, (H, W, K) = token
            pts: List[Tuple[float, float, float]] = []
            for x, y, heat, oy, ox in rows_mem.host():
                score = float(_sigmoid(heat))
                if self.offset_mode and buf.num_tensors > 1:
                    px = (x / max(W - 1, 1)) * self.in_w + float(ox)
                    py = (y / max(H - 1, 1)) * self.in_h + float(oy)
                else:
                    px = (x + 0.5) / W * self.in_w
                    py = (y + 0.5) / H * self.in_h
                pts.append((px / self.in_w, py / self.in_h, score))
            return self._finish(pts, buf)
        return self.decode(token, config)

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        pts = self.keypoints(buf)
        return self._finish(pts, buf)

    def _finish(self, pts, buf: Buffer) -> Buffer:
        from .util import new_canvas

        canvas = new_canvas(self.out_w, self.out_h)
        coords = []
        for nx, ny, score in pts:
            x, y = int(nx * self.out_w), int(ny * self.out_h)
            coords.append((x, y, score))
            if score >= self.score_threshold:
                draw_disc(canvas, x, y, 3)
        for a, b in _DEFAULT_EDGES:
            if a < len(coords) and b < len(coords) \
                    and coords[a][2] >= self.score_threshold \
                    and coords[b][2] >= self.score_threshold:
                draw_line(canvas, coords[a][0], coords[a][1],
                          coords[b][0], coords[b][1])
        out = buf.with_memories([TensorMemory(canvas)])
        out.meta["keypoints"] = pts
        return out
