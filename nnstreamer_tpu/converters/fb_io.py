"""FlexBuffers / FlatBuffers tensor serialization (decoder + converter pairs).

Reference-exact wire layouts, interoperable with upstream peers:

* FlexBuffers (tensordec-flexbuf.cc:26-33, tensor_converter_flexbuf.cc:107-146):
  ``Map { "num_tensors": UInt, "rate_n": Int, "rate_d": Int, "format": Int,
  "tensor_#i": Vector[ String name, Int type_enum, TypedVector dims(rank 4),
  Blob data ] }`` — dims zero-rank-padded with 1 to NNS_TENSOR_RANK_LIMIT=4
  (tensor_typedef.h:34), dtype as the reference ``tensor_type`` enum
  (tensor_typedef.h:155-166).

* FlatBuffers (ext/nnstreamer/include/nnstreamer.fbs:12-53):
  ``table Tensors { num_tensor:int; fr:frame_rate(struct rate_n,rate_d);
  tensor:[Tensor]; format:Tensor_format }``,
  ``table Tensor { name:string; type:Tensor_type; dimension:[uint32];
  data:[ubyte] }`` — built/read with the runtime ``flatbuffers`` API (no
  codegen step), field slots matching flatc's vtable layout for that schema.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional, Tuple

import flatbuffers  # gates registration: decoders/__init__ skips on ImportError
import numpy as np
from flatbuffers import flexbuffers
from flatbuffers import number_types as N

from ..core.buffer import Buffer, TensorMemory
from ..core.types import (
    Caps,
    TensorDType,
    TensorFormat,
    TensorInfo,
    TensorsConfig,
    TensorsInfo,
)
from ..decoders.base import Decoder, register_decoder
from . import register_converter

#: NNS_TENSOR_RANK_LIMIT (tensor_typedef.h:34)
RANK_LIMIT = 4

#: reference ``tensor_type`` enum (tensor_typedef.h:155-166; identical to
#: nnstreamer.fbs Tensor_type)
_DTYPE_TO_ENUM = {
    TensorDType.INT32: 0, TensorDType.UINT32: 1,
    TensorDType.INT16: 2, TensorDType.UINT16: 3,
    TensorDType.INT8: 4, TensorDType.UINT8: 5,
    TensorDType.FLOAT64: 6, TensorDType.FLOAT32: 7,
    TensorDType.INT64: 8, TensorDType.UINT64: 9,
}
_ENUM_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ENUM.items()}
_FORMAT_TO_ENUM = {TensorFormat.STATIC: 0, TensorFormat.FLEXIBLE: 1,
                   TensorFormat.SPARSE: 2}
_ENUM_TO_FORMAT = {v: k for k, v in _FORMAT_TO_ENUM.items()}


def _dtype_enum(info: TensorInfo) -> int:
    e = _DTYPE_TO_ENUM.get(info.dtype)
    if e is None:
        raise ValueError(
            f"dtype {info.dtype} has no reference tensor_type enum value "
            "(bf16/f16 are TPU-local; typecast before serializing)")
    return e


def _padded_dims(info: TensorInfo) -> List[int]:
    dims = [int(d) for d in info.dims[:RANK_LIMIT]]
    if len(info.dims) > RANK_LIMIT:
        raise ValueError(
            f"rank {len(info.dims)} exceeds the wire format's "
            f"NNS_TENSOR_RANK_LIMIT={RANK_LIMIT}")
    return dims + [1] * (RANK_LIMIT - len(dims))


def _trimmed_info(dims: Tuple[int, ...], type_enum: int,
                  name: str) -> TensorInfo:
    dt = _ENUM_TO_DTYPE.get(type_enum)
    if dt is None:
        raise ValueError(f"unknown tensor_type enum {type_enum}")
    trimmed = list(dims)
    while len(trimmed) > 1 and trimmed[-1] in (1, 0):
        trimmed.pop()
    if any(d <= 0 for d in trimmed):
        raise ValueError(f"invalid dimension {dims}")
    return TensorInfo(tuple(trimmed), dt, name or None)


# ---------------------------------------------------------------------------- #
# FlexBuffers (schema-less)
# ---------------------------------------------------------------------------- #

def frame_to_flexbuf(buf: Buffer, config: TensorsConfig = None) -> bytes:
    rate = config.rate if config is not None and config.rate else Fraction(0, 1)
    fmt = config.info.format if config is not None else TensorFormat.STATIC
    b = flexbuffers.Builder()
    with b.Map():
        b.Key("num_tensors"); b.UInt(len(buf.memories), 4)
        b.Key("rate_n"); b.Int(rate.numerator)
        b.Key("rate_d"); b.Int(rate.denominator)
        b.Key("format"); b.Int(_FORMAT_TO_ENUM.get(fmt, 0))
        for i, m in enumerate(buf.memories):
            b.Key(f"tensor_{i}")
            with b.Vector():
                b.String(m.info.name or "")
                b.Int(_dtype_enum(m.info))
                b.TypedVectorFromElements(_padded_dims(m.info))
                b.Blob(m.tobytes())
    return bytes(b.Finish())


def flexbuf_to_frame(data: bytes) -> Tuple[Buffer, Fraction]:
    root = flexbuffers.GetRoot(bytearray(data)).AsMap
    num = root["num_tensors"].AsInt
    if num < 0 or num > 16:  # NNS_TENSOR_SIZE_LIMIT
        raise ValueError(f"flexbuf: num_tensors {num} out of range")
    rate = Fraction(root["rate_n"].AsInt, max(root["rate_d"].AsInt, 1))
    mems: List[TensorMemory] = []
    for i in range(num):
        t = root[f"tensor_{i}"].AsVector
        dims = tuple(e.AsInt for e in t[2].AsTypedVector)
        info = _trimmed_info(dims, t[1].AsInt, t[0].AsString)
        payload = bytes(t[3].AsBlob)
        if len(payload) != info.size_bytes:
            raise ValueError(
                f"flexbuf tensor {i}: {len(payload)} payload bytes for "
                f"{info.dim_string}:{info.dtype} ({info.size_bytes} expected)")
        mems.append(TensorMemory.from_bytes(payload, info))
    return Buffer(mems), rate


# ---------------------------------------------------------------------------- #
# FlatBuffers (nnstreamer.fbs layout)
# ---------------------------------------------------------------------------- #

_SLOT = lambda i: 4 + 2 * i  # vtable offset of field slot i


def frame_to_flatbuf(buf: Buffer, config: TensorsConfig = None) -> bytes:
    rate = config.rate if config is not None and config.rate else Fraction(0, 1)
    fmt = config.info.format if config is not None else TensorFormat.STATIC
    b = flatbuffers.Builder(1024)
    tensor_offs = []
    for m in buf.memories:
        name = b.CreateString(m.info.name or "")
        data = b.CreateByteVector(m.tobytes())
        dims = _padded_dims(m.info)
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(int(d))
        dims_off = b.EndVector()
        # table Tensor { name:0, type:1 (default NNS_END=10),
        #               dimension:2, data:3 }
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name, 0)
        b.PrependInt32Slot(1, _dtype_enum(m.info), 10)
        b.PrependUOffsetTRelativeSlot(2, dims_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    tvec = b.EndVector()
    # table Tensors { num_tensor:0, fr:1 (inline struct), tensor:2, format:3 }
    b.StartObject(4)
    b.PrependInt32Slot(0, len(tensor_offs), 0)
    b.Prep(4, 8)  # struct frame_rate { rate_n:int; rate_d:int }
    b.PrependInt32(rate.denominator)
    b.PrependInt32(rate.numerator)
    b.PrependStructSlot(1, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(2, tvec, 0)
    b.PrependInt32Slot(3, _FORMAT_TO_ENUM.get(fmt, 0), 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def flatbuf_to_frame(data: bytes) -> Tuple[Buffer, Fraction]:
    raw = bytearray(data)
    root = flatbuffers.table.Table(
        raw, flatbuffers.encode.Get(N.UOffsetTFlags.packer_type, raw, 0))

    def i32(tab, slot, default=0):
        o = tab.Offset(_SLOT(slot))
        return tab.Get(N.Int32Flags, o + tab.Pos) if o else default

    # fr: inline frame_rate struct at slot 1
    fo = root.Offset(_SLOT(1))
    if fo:
        rate_n = root.Get(N.Int32Flags, fo + root.Pos)
        rate_d = root.Get(N.Int32Flags, fo + root.Pos + 4)
    else:
        rate_n, rate_d = 0, 0
    rate = Fraction(rate_n, max(rate_d, 1))
    num = i32(root, 0)
    mems: List[TensorMemory] = []
    o = root.Offset(_SLOT(2))
    n = root.VectorLen(o) if o else 0
    if num and num != n:
        raise ValueError(f"flatbuf: num_tensor {num} != vector length {n}")
    for i in range(n):
        t = flatbuffers.table.Table(raw, root.Indirect(root.Vector(o) + 4 * i))
        no = t.Offset(_SLOT(0))
        name = t.String(no + t.Pos).decode() if no else ""
        type_enum = i32(t, 1, 10)
        so = t.Offset(_SLOT(2))
        dims = tuple(t.Get(N.Uint32Flags, t.Vector(so) + 4 * j)
                     for j in range(t.VectorLen(so))) if so else ()
        bo = t.Offset(_SLOT(3))
        if bo:
            start, ln = t.Vector(bo), t.VectorLen(bo)
            payload = bytes(raw[start:start + ln])
        else:
            payload = b""
        info = _trimmed_info(dims, type_enum, name)
        if len(payload) != info.size_bytes:
            raise ValueError(
                f"flatbuf tensor {i}: {len(payload)} payload bytes for "
                f"{info.dim_string}:{info.dtype} ({info.size_bytes} expected)")
        mems.append(TensorMemory.from_bytes(payload, info))
    return Buffer(mems), rate


# ---------------------------------------------------------------------------- #
# element plumbing: decoder modes + converter subplugins
# ---------------------------------------------------------------------------- #

class _SerializeDecoder(Decoder):
    ENCODE = None  # staticmethod set by subclass

    def out_caps(self, config: TensorsConfig) -> Caps:
        # reference media names (``other/flexbuf`` etc.): tensor_converter
        # auto-dispatches the matching converter subplugin from these, so
        # ``tensor_decoder mode=flexbuf ! other/flexbuf !
        # tensor_converter`` chains run verbatim
        return Caps(f"other/{self.MODE}")

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        blob = np.frombuffer(type(self).ENCODE(buf, config), np.uint8).copy()
        return buf.with_memories([TensorMemory(blob)])


@register_decoder
class FlexBufDecoder(_SerializeDecoder):
    """tensors → FlexBuffers blobs (tensordec-flexbuf.cc layout)."""

    MODE = "flexbuf"
    ENCODE = staticmethod(frame_to_flexbuf)


@register_decoder
class FlatBufDecoder(_SerializeDecoder):
    """tensors → FlatBuffers frames (nnstreamer.fbs layout)."""

    MODE = "flatbuf"
    ENCODE = staticmethod(frame_to_flatbuf)


def _make_converter(parse):
    def convert(buf: Buffer, props) -> tuple:
        data = b"".join(m.tobytes() for m in buf.memories)
        frame, rate = parse(data)
        cfg = TensorsConfig(TensorsInfo(tuple(m.info for m in frame.memories)),
                            rate)
        return frame.memories, cfg
    return convert


register_converter("flexbuf", _make_converter(flexbuf_to_frame))
register_converter("flatbuf", _make_converter(flatbuf_to_frame))
