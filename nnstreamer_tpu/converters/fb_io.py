"""FlexBuffers / FlatBuffers tensor serialization (decoder + converter pairs).

Reference: ext/nnstreamer/tensor_decoder/tensordec-flexbuf.cc and
tensordec-flatbuf.cc + tensor_converter/tensor_converter_flexbuf.cc and
tensor_converter_flatbuf.cc — tensors ↔ (Flex|Flat)Buffers blobs for interop
links. The reference compiles a schema with flatc; here the FlatBuffers frame
table is built/read with the runtime ``flatbuffers.Builder``/``Table`` API
directly (no codegen step), and FlexBuffers uses the schema-less API.

Frame layout (both formats carry the same fields):
  rate_n/rate_d  — stream framerate
  tensors[]      — name, dtype (string), dims (int vector, innermost-first
                   like TensorInfo.dims), data (byte blob)
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Tuple

import flatbuffers  # gates registration: decoders/__init__ skips on ImportError
import numpy as np
from flatbuffers import flexbuffers
from flatbuffers import number_types as N

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorDType, TensorInfo, TensorsConfig, TensorsInfo
from ..decoders.base import Decoder, register_decoder
from . import register_converter


# ---------------------------------------------------------------------------- #
# FlexBuffers (schema-less)
# ---------------------------------------------------------------------------- #

def frame_to_flexbuf(buf: Buffer, config: TensorsConfig = None) -> bytes:
    rate = config.rate if config is not None and config.rate else Fraction(0, 1)
    b = flexbuffers.Builder()
    with b.Map():
        b.Key("rate_n"); b.Int(rate.numerator)
        b.Key("rate_d"); b.Int(rate.denominator)
        b.Key("tensors")
        with b.Vector():
            for m in buf.memories:
                with b.Map():
                    b.Key("name"); b.String(m.info.name or "")
                    b.Key("dtype"); b.String(str(m.info.dtype))
                    b.Key("dims")
                    with b.TypedVector():
                        for d in m.info.dims:
                            b.Int(int(d))
                    b.Key("data"); b.Blob(m.tobytes())
    return bytes(b.Finish())


def flexbuf_to_frame(data: bytes) -> Tuple[Buffer, Fraction]:
    root = flexbuffers.GetRoot(bytearray(data)).AsMap
    rate = Fraction(root["rate_n"].AsInt, max(root["rate_d"].AsInt, 1))
    mems: List[TensorMemory] = []
    for t in root["tensors"].AsVector:
        tm = t.AsMap
        info = TensorInfo(
            tuple(e.AsInt for e in tm["dims"].AsTypedVector),
            TensorDType.parse(tm["dtype"].AsString),
            tm["name"].AsString or None)
        mems.append(TensorMemory.from_bytes(bytes(tm["data"].AsBlob), info))
    return Buffer(mems), rate


# ---------------------------------------------------------------------------- #
# FlatBuffers (schema'd: Frame{rate_n, rate_d, tensors:[Tensor]},
#              Tensor{name, dtype, dims:[int32], data:[ubyte]})
# ---------------------------------------------------------------------------- #

_SLOT = lambda i: 4 + 2 * i  # vtable offset of field slot i


def frame_to_flatbuf(buf: Buffer, config: TensorsConfig = None) -> bytes:
    rate = config.rate if config is not None and config.rate else Fraction(0, 1)
    b = flatbuffers.Builder(1024)
    tensor_offs = []
    for m in buf.memories:
        name = b.CreateString(m.info.name or "")
        dtype = b.CreateString(str(m.info.dtype))
        data = b.CreateByteVector(m.tobytes())
        dims = m.info.dims
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependInt32(int(d))
        dims_off = b.EndVector()
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name, 0)
        b.PrependUOffsetTRelativeSlot(1, dtype, 0)
        b.PrependUOffsetTRelativeSlot(2, dims_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    tvec = b.EndVector()
    b.StartObject(3)
    b.PrependInt32Slot(0, rate.numerator, 0)
    b.PrependInt32Slot(1, rate.denominator, 0)
    b.PrependUOffsetTRelativeSlot(2, tvec, 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def flatbuf_to_frame(data: bytes) -> Tuple[Buffer, Fraction]:
    raw = bytearray(data)
    root = flatbuffers.table.Table(
        raw, flatbuffers.encode.Get(N.UOffsetTFlags.packer_type, raw, 0))

    def i32(tab, slot, default=0):
        o = tab.Offset(_SLOT(slot))
        return tab.Get(N.Int32Flags, o + tab.Pos) if o else default

    rate = Fraction(i32(root, 0), max(i32(root, 1), 1))
    mems: List[TensorMemory] = []
    o = root.Offset(_SLOT(2))
    n = root.VectorLen(o) if o else 0
    for i in range(n):
        t = flatbuffers.table.Table(raw, root.Indirect(root.Vector(o) + 4 * i))
        no = t.Offset(_SLOT(0))
        name = t.String(no + t.Pos).decode() if no else ""
        do = t.Offset(_SLOT(1))
        dtype = t.String(do + t.Pos).decode() if do else "uint8"
        so = t.Offset(_SLOT(2))
        dims = tuple(t.Get(N.Int32Flags, t.Vector(so) + 4 * j)
                     for j in range(t.VectorLen(so))) if so else ()
        bo = t.Offset(_SLOT(3))
        if bo:
            start, ln = t.Vector(bo), t.VectorLen(bo)
            payload = bytes(raw[start:start + ln])
        else:
            payload = b""
        info = TensorInfo(dims, TensorDType.parse(dtype), name or None)
        if len(payload) != info.size_bytes:
            raise ValueError(
                f"flatbuf tensor {i}: {len(payload)} payload bytes for "
                f"{info.dim_string}:{info.dtype} ({info.size_bytes} expected)")
        mems.append(TensorMemory.from_bytes(payload, info))
    return Buffer(mems), rate


# ---------------------------------------------------------------------------- #
# element plumbing: decoder modes + converter subplugins
# ---------------------------------------------------------------------------- #

class _SerializeDecoder(Decoder):
    ENCODE = None  # staticmethod set by subclass

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("application/octet-stream")

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        blob = np.frombuffer(type(self).ENCODE(buf, config), np.uint8).copy()
        return buf.with_memories([TensorMemory(blob)])


@register_decoder
class FlexBufDecoder(_SerializeDecoder):
    """tensors → FlexBuffers blobs (tensordec-flexbuf.cc analog)."""

    MODE = "flexbuf"
    ENCODE = staticmethod(frame_to_flexbuf)


@register_decoder
class FlatBufDecoder(_SerializeDecoder):
    """tensors → FlatBuffers frames (tensordec-flatbuf.cc analog)."""

    MODE = "flatbuf"
    ENCODE = staticmethod(frame_to_flatbuf)


def _make_converter(parse):
    def convert(buf: Buffer, props) -> tuple:
        data = b"".join(m.tobytes() for m in buf.memories)
        frame, rate = parse(data)
        cfg = TensorsConfig(TensorsInfo(tuple(m.info for m in frame.memories)),
                            rate)
        return frame.memories, cfg
    return convert


register_converter("flexbuf", _make_converter(flexbuf_to_frame))
register_converter("flatbuf", _make_converter(flatbuf_to_frame))
