"""Reference python custom-script converter/decoder loaders.

The reference dispatches ``tensor_converter mode=custom-script:<path.py>``
and ``tensor_decoder mode=custom-script:<path.py>`` to user scripts with
this contract (tensor_converter_python3.cc / tensordec-python3.cc; its
own test scripts custom_converter.py / custom_decoder.py):

  * converter: ``class CustomConverter`` with
    ``convert(input_array) -> (tensors_info, raw_data, rate_n, rate_d)``
    — input is a list of raw uint8 arrays, ``tensors_info`` a list of
    ``nns.TensorShape`` (innermost-first dims + numpy dtype), ``raw_data``
    the flat per-tensor payloads;
  * decoder: ``class CustomDecoder`` with ``getOutCaps() -> bytes`` (the
    output media caps string) and
    ``decode(raw_data, in_info, rate_n, rate_d) -> bytes``.

Both may ``import nnstreamer_python as nns`` — the shim in
filters/nns_python_compat.py provides it. Loaded objects are memoized per
path so a pipeline reload does not re-exec the script.
"""

from __future__ import annotations

import importlib.util
import os
from fractions import Fraction
from typing import Any, Callable, Dict, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorsConfig, TensorsInfo
from ..decoders.base import Decoder


def _load_script(path: str, class_name: str) -> Any:
    """Load the script's class and return a FRESH instance — the reference
    instantiates per element, so two pipelines sharing a stateful script
    must not share one object (the module itself is cached by
    load_script_module)."""
    cls = getattr(load_script_module(path), class_name, None)
    if cls is None:
        raise ValueError(f"{path}: must define class {class_name}")
    return cls()


_module_cache: Dict[str, Any] = {}


def load_script_module(path: str):
    """Exec a user script once per path (with the nnstreamer_python shim
    installed) — shared loader for python3 filters, converters, and
    decoders."""
    from ..filters.nns_python_compat import install_shim

    install_shim()
    key = os.path.abspath(path)
    if key in _module_cache:
        return _module_cache[key]
    if not os.path.isfile(path):
        raise FileNotFoundError(f"custom-script not found: {path}")
    spec = importlib.util.spec_from_file_location(
        f"nns_tpu_script_{abs(hash(key))}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _module_cache[key] = mod
    return mod


def load_script_converter(path: str) -> Callable:
    """``mode=custom-script:<path>`` → a converter subplugin callable
    ``(buf, props) -> (arrays, TensorsConfig)``."""
    from ..filters.nns_python_compat import shapes_to_info

    obj = _load_script(path, "CustomConverter")

    def convert(buf: Buffer, props: Any) -> Tuple[list, TensorsConfig]:
        raw = [np.frombuffer(m.tobytes(), np.uint8) for m in buf.memories]
        shapes, payloads, rate_n, rate_d = obj.convert(raw)
        info = shapes_to_info(shapes)
        arrays = []
        for t, payload in zip(info, payloads):
            flat = np.frombuffer(
                np.asarray(payload).tobytes(), t.dtype.np_dtype)
            arrays.append(flat.reshape(t.shape))
        cfg = TensorsConfig(info, Fraction(int(rate_n), max(int(rate_d), 1)))
        return arrays, cfg

    return convert


class ScriptDecoder(Decoder):
    """``tensor_decoder mode=custom-script:<path>`` — the Decoder contract
    (incl. the base submit/complete pipelined path) over a reference
    CustomDecoder object."""

    MODE = "custom-script"

    def __init__(self, path: str):
        super().__init__()
        self._obj = _load_script(path, "CustomDecoder")

    def out_caps(self, config: TensorsConfig) -> Caps:
        from ..graph.parse import parse_caps_string

        raw = self._obj.getOutCaps()
        caps_str = (raw.decode() if isinstance(raw, (bytes, bytearray))
                    else str(raw)).strip()
        try:
            return parse_caps_string(caps_str)  # full fields forwarded
        except Exception:
            return Caps(caps_str.split(",")[0].strip())

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        from ..filters.nns_python_compat import info_to_shapes

        raw = [np.ravel(m.host()) for m in buf.memories]
        infos: TensorsInfo = TensorsInfo(
            tuple(m.info for m in buf.memories))
        rate = config.rate or Fraction(0, 1)
        out = self._obj.decode(raw, info_to_shapes(infos),
                               rate.numerator, rate.denominator)
        blob = np.frombuffer(bytes(out), np.uint8).copy()
        return buf.with_memories([TensorMemory(blob)])
