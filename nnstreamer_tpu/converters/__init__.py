"""Converter subplugins (media → tensor): register custom converters under
SubpluginType.CONVERTER; the built-in media handlers live in
elements/converter.py.

A custom converter is ``fn(buf, props) -> (arrays, TensorsConfig)`` registered
via ``register_converter`` (reference NNStreamerExternalConverter,
nnstreamer_plugin_api_converter.h:41-85).
"""

from ..core.registry import SubpluginType, register_subplugin, unregister_subplugin


def register_converter(name: str, fn, *, replace: bool = True) -> None:
    register_subplugin(SubpluginType.CONVERTER, name, fn, replace=replace)


def unregister_converter(name: str) -> None:
    unregister_subplugin(SubpluginType.CONVERTER, name)


__all__ = ["register_converter", "unregister_converter"]
