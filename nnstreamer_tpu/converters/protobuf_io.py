"""Protobuf tensor serialization: decoder mode=protobuf + converter subplugin.

Reference: ext/nnstreamer/tensor_decoder/tensordec-protobuf.cc +
tensor_converter/tensor_converter_protobuf.cc (+ extra/nnstreamer_protobuf.cc)
— tensors ↔ protobuf messages for interop links. Schema:
converters/proto/tensors.proto (compiled with protoc).
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.types import Caps, TensorDType, TensorInfo, TensorsConfig, TensorsInfo
from ..decoders.base import Decoder, register_decoder
from . import register_converter
from .proto import tensors_pb2


def frame_to_proto(buf: Buffer) -> bytes:
    msg = tensors_pb2.TensorFrame()
    if buf.pts is not None:
        msg.pts_ns = buf.pts
    if buf.duration is not None:
        msg.duration_ns = buf.duration
    if buf.offset is not None:
        msg.offset = buf.offset
    for m in buf.memories:
        t = msg.tensors.add()
        t.dtype = str(m.info.dtype)
        t.dims.extend(m.info.dims)
        if m.info.name:
            t.name = m.info.name
        t.data = m.tobytes()
    return msg.SerializeToString()


def proto_to_frame(data: bytes) -> Buffer:
    msg = tensors_pb2.TensorFrame()
    msg.ParseFromString(bytes(data))
    mems = []
    for t in msg.tensors:
        info = TensorInfo(tuple(t.dims), TensorDType.parse(t.dtype),
                          t.name or None)
        mems.append(TensorMemory.from_bytes(t.data, info))
    return Buffer(mems, pts=msg.pts_ns or None,
                  duration=msg.duration_ns or None,
                  offset=msg.offset or None)


@register_decoder
class ProtobufDecoder(Decoder):
    """tensors → other/protobuf frames (reference media name — the
    converter auto-dispatches its protobuf subplugin from the caps)."""

    MODE = "protobuf"

    def out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("other/protobuf")

    def decode(self, buf: Buffer, config: TensorsConfig) -> Buffer:
        blob = np.frombuffer(frame_to_proto(buf), np.uint8).copy()
        return buf.with_memories([TensorMemory(blob)])


def _protobuf_converter(buf: Buffer, props) -> tuple:
    data = b"".join(m.tobytes() for m in buf.memories)
    frame = proto_to_frame(data)
    cfg = TensorsConfig(TensorsInfo(tuple(m.info for m in frame.memories)))
    return frame.memories, cfg


register_converter("protobuf", _protobuf_converter)
