"""tensor_query wire protocol.

Reference: gst/nnstreamer/tensor_query/tensor_query_common.c/.h — commands
REQUEST_INFO/RESPOND_APPROVE/RESPOND_DENY/TRANSFER_START/DATA/END/CLIENT_ID
(:42-51) with a C-struct data header (:57-68) over raw GSocket TCP.

Redesigned framing (still plain TCP; one message per frame instead of the
reference's START/DATA×N/END triple — fewer round trips on the offload hot
path):

    magic   u32  0x4E515250 ("NQRP")
    cmd     u8
    meta_len u32 (LE)
    payload_len u64 (LE)
    meta    JSON (caps/config, pts/duration, tensor sizes, client id)
    payload concatenated tensor blobs (each = 128B flex meta header + raw
            bytes; sparse tensors use the sparse wire layout)

Payloads are framework-agnostic bytes: the server can decode to host numpy
or jax device arrays. Compression: ``sparse=true`` in meta marks
sparse-encoded payloads (tensor_sparse_enc on the link, §2.5).
"""

from __future__ import annotations

import enum
import json
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.meta import META_SIZE, TensorMetaInfo, unwrap_flex, wrap_flex
from ..core.types import TensorFormat
from ..obs import metrics as _obs
from ..obs import tracing as _tracing

MAGIC = 0x4E515250
_HEADER = struct.Struct("<IBIQ")
MAX_MESSAGE = 1 << 31


class Cmd(enum.IntEnum):
    INFO_REQ = 1      # client → server: hello + stream caps
    INFO_APPROVE = 2  # server → client: accepted (+server caps)
    INFO_DENY = 3
    DATA = 4          # client → server: one frame
    RESULT = 5        # server → client: one result frame
    ERROR = 6
    PING = 7
    PONG = 8
    # chunked transfer (reference TRANSFER_START/DATA/END,
    # tensor_query_common.h:42-68): payloads over CHUNK_SIZE stream as
    # bounded chunks with a per-chunk receive timeout, assembled into one
    # preallocated buffer (no monolithic send, no unbounded recv stall)
    CHUNK_START = 9
    CHUNK_DATA = 10
    CHUNK_END = 11
    # fleet observability piggyback (obs/fleet.py): a client ships its
    # metric/health/span snapshot ahead of a DATA frame; fire-and-forget
    # (no reply frame — the data stream must not stall on telemetry)
    OBS_PUSH = 12
    # disaggregated serving (serving/disagg.py): one finished KV radix
    # path migrates prefill→decode backend — meta carries the chunk
    # keys + dtype/layout header, the payload the concatenated page
    # bits (auto-chunked like DATA), and the receiver answers RESULT
    # (pages spliced) or ERROR (rejected — geometry/pool)
    KV_PAGE_XFER = 13


class QueryProtocolError(RuntimeError):
    pass


#: wire-level telemetry shared by BOTH roles (client and server live in
#: one process in tests and hybrid deployments): message counts by
#: direction x command, and payload bytes by direction. Registered at
#: import; recording is a no-op until metrics are enabled.
_MSG_TOTAL = _obs.registry().counter(
    "nnstpu_query_messages_total",
    "Query protocol messages by direction and command",
    ("direction", "cmd"))
_BYTES_TOTAL = _obs.registry().counter(
    "nnstpu_query_bytes_total",
    "Query protocol payload bytes by direction", ("direction",))


#: chaos injection point (resilience/chaos.py installs/clears this):
#: called as ``hook(direction, cmd, meta, payload, endpoint) ->
#: payload|None`` at the top of send_message ("send") and per received
#: frame ("recv"); ``endpoint`` is the socket's peer as "host:port"
#: (None when unresolvable) so a plan can target one backend of a
#: routed set. None return drops the frame, a raise propagates into
#: the caller's normal error handling. Disabled cost: one global load
#: + None check — the peer lookup only happens with a hook installed.
CHAOS_HOOK = None


def _peer_of(sock: socket.socket) -> Optional[str]:
    """The socket's peer as ``"host:port"`` — chaos targeting only, so
    failure is answered with None, never an exception."""
    try:
        peer = sock.getpeername()
        return f"{peer[0]}:{peer[1]}"
    except Exception:
        return None

#: max bytes per wire chunk; also the granularity of receive timeouts
CHUNK_SIZE = 1 << 20
#: a chunk that doesn't arrive within this window fails the transfer —
#: per-chunk progress detection instead of one whole-payload stall
CHUNK_TIMEOUT = 15.0


def pack_message(cmd: Cmd, meta: Dict[str, Any], payload: bytes = b"") -> bytes:
    meta_b = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(MAGIC, int(cmd), len(meta_b), len(payload)) + meta_b + payload


def _pack_frame_header(cmd: Cmd, meta: Dict[str, Any],
                       payload_len: int) -> bytes:
    """Header + meta only, declaring ``payload_len`` bytes to follow —
    lets send_message stream a memoryview payload without concatenating
    (and therefore copying) it into one bytes object first."""
    meta_b = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(MAGIC, int(cmd), len(meta_b), payload_len) + meta_b


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes (list-accumulated; O(n) for large payloads)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


_recv_exact = recv_exact


def _recv_one(sock: socket.socket) -> Tuple[Cmd, Dict[str, Any], bytes]:
    hdr = _recv_exact(sock, _HEADER.size)
    magic, cmd, meta_len, payload_len = _HEADER.unpack(hdr)
    if magic != MAGIC:
        raise QueryProtocolError(f"bad magic 0x{magic:08x}")
    if payload_len > MAX_MESSAGE:
        raise QueryProtocolError(f"payload too large: {payload_len}")
    meta = json.loads(_recv_exact(sock, meta_len) or b"{}")
    payload = _recv_exact(sock, payload_len) if payload_len else b""
    return Cmd(cmd), meta, payload


def recv_message(sock: socket.socket,
                 chunk_timeout: float = CHUNK_TIMEOUT
                 ) -> Tuple[Cmd, Dict[str, Any], bytes]:
    cmd, meta, payload = _recv_one(sock)
    if CHAOS_HOOK is not None:
        payload = CHAOS_HOOK("recv", cmd, meta, payload, _peer_of(sock))
        if payload is None:
            # frame dropped by the fault plan: deliver the next one —
            # from the caller's view the frame simply never arrived
            return recv_message(sock, chunk_timeout)
    if cmd is not Cmd.CHUNK_START:
        _MSG_TOTAL.labels("recv", cmd.name).inc()
        _BYTES_TOTAL.labels("recv").inc(len(payload))
        return cmd, meta, payload
    # chunked transfer: assemble into a preallocated buffer under a
    # per-chunk timeout
    try:
        total = int(meta.pop("chunked_total"))
        inner = Cmd(int(meta.pop("chunked_cmd")))
    except (KeyError, ValueError, TypeError) as e:
        # TypeError included: {"chunked_total": null} decodes to None
        # and int(None) must fail the transfer, not the receive loop
        raise QueryProtocolError(f"bad CHUNK_START meta: {e}")
    if total > MAX_MESSAGE or total < 0:
        raise QueryProtocolError(f"chunked payload too large: {total}")
    # chunked assembly is the one receive with real duration: time it
    # as a span parented on the sender's context when one rode along
    rspan = _tracing.NOOP_SPAN
    if _tracing.enabled():
        rctx = _tracing.ctx_from_wire(meta.get(_tracing.TRACE_META_KEY))
        if rctx is not None:
            _tracing.store().mark_export(rctx.trace_id)
            rspan = _tracing.start_span(
                "query.recv", parent=rctx,
                attrs={"cmd": Cmd(inner).name, "bytes": total})
    assembled = bytearray(total)
    got = 0
    prev_timeout = sock.gettimeout()
    sock.settimeout(chunk_timeout)
    try:
        while True:
            try:
                ccmd, cmeta, chunk = _recv_one(sock)
            except socket.timeout:
                raise QueryProtocolError(
                    f"chunk timeout after {got}/{total} bytes "
                    f"({chunk_timeout}s without progress)")
            if ccmd is Cmd.CHUNK_DATA:
                off = int(cmeta.get("off", -1))
                if off != got:
                    # offsets must be strictly sequential: a duplicate or
                    # overlapping chunk would otherwise inflate the byte
                    # counter and let a hole pass the completeness check
                    raise QueryProtocolError(
                        f"chunk out of order: off={off}, expected {got}")
                if off + len(chunk) > total:
                    raise QueryProtocolError(
                        f"chunk out of bounds: off={off} len={len(chunk)}")
                assembled[off:off + len(chunk)] = chunk
                got += len(chunk)
            elif ccmd is Cmd.CHUNK_END:
                if got != total:
                    raise QueryProtocolError(
                        f"chunked transfer incomplete: {got}/{total} bytes")
                _MSG_TOTAL.labels("recv", inner.name).inc()
                _BYTES_TOTAL.labels("recv").inc(total)
                rspan.end()
                return inner, meta, bytes(assembled)
            else:
                raise QueryProtocolError(
                    f"unexpected {ccmd.name} inside chunked transfer")
    except QueryProtocolError:
        rspan.set_attribute("error", True)
        rspan.end()
        raise
    finally:
        sock.settimeout(prev_timeout)


def send_message(sock: socket.socket, cmd: Cmd, meta: Dict[str, Any],
                 payload: bytes = b"") -> None:
    if CHAOS_HOOK is not None:
        payload = CHAOS_HOOK("send", cmd, meta, payload, _peer_of(sock))
        if payload is None:
            return  # frame silently eaten by the installed fault plan
    _MSG_TOTAL.labels("sent", cmd.name).inc()
    _BYTES_TOTAL.labels("sent").inc(len(payload))
    span = _tracing.NOOP_SPAN
    if _tracing.enabled():
        # stamp the caller's context into the wire meta so the peer can
        # adopt it as a remote parent; the send itself becomes a span.
        # Disabled path: no flag set, no `trace` key, zero wire bytes
        # added — the cross-wire format is strictly additive.
        ctx = _tracing.current_context()
        if ctx is not None and _tracing.TRACE_META_KEY not in meta:
            meta = dict(meta)
            meta[_tracing.TRACE_META_KEY] = ctx.to_wire()
            # the trace id now exists on two hosts: mark it so fleet
            # push (when on) exports this side's completed spans
            _tracing.store().mark_export(ctx.trace_id)
            span = _tracing.start_span(
                "query.send", parent=ctx,
                attrs={"cmd": cmd.name, "bytes": len(payload)})
    try:
        if len(payload) <= CHUNK_SIZE:
            sock.sendall(pack_message(cmd, meta, payload))
            return
        start = dict(meta, chunked_cmd=int(cmd), chunked_total=len(payload))
        sock.sendall(pack_message(Cmd.CHUNK_START, start))
        view = memoryview(payload)
        for off in range(0, len(payload), CHUNK_SIZE):
            chunk = view[off:off + CHUNK_SIZE]
            # header+meta first, then the memoryview slice straight to
            # the socket: the payload bytes are never copied on the
            # send side (sendall accepts buffer-protocol objects)
            sock.sendall(_pack_frame_header(
                Cmd.CHUNK_DATA, {"off": off}, len(chunk)))
            sock.sendall(chunk)
        sock.sendall(pack_message(Cmd.CHUNK_END, {}))
    finally:
        span.end()


# --------------------------------------------------------------------------- #
# Buffer ↔ payload
# --------------------------------------------------------------------------- #

def buffer_to_payload(buf: Buffer, sparse: bool = False) -> Tuple[Dict[str, Any], bytes]:
    from ..elements.sparse import sparse_encode

    blobs: List[bytes] = []
    for m in buf.memories:
        if sparse:
            blobs.append(sparse_encode(m.host(), m.info))
        else:
            blobs.append(wrap_flex(m.tobytes(), m.info))
    meta = {
        "pts": buf.pts,
        "duration": buf.duration,
        "offset": buf.offset,
        "num_tensors": len(blobs),
        "sizes": [len(b) for b in blobs],
        "sparse": sparse,
    }
    return meta, b"".join(blobs)


def payload_to_buffer(meta: Dict[str, Any], payload: bytes) -> Buffer:
    from ..elements.sparse import sparse_decode

    mems: List[TensorMemory] = []
    off = 0
    for size in meta.get("sizes", []):
        blob = payload[off:off + size]
        off += size
        if meta.get("sparse"):
            arr, info = sparse_decode(blob)
            mems.append(TensorMemory(arr, info))
        else:
            tmeta, raw = unwrap_flex(blob)
            mems.append(TensorMemory.from_bytes(raw[:tmeta.info.size_bytes],
                                                tmeta.info))
    return Buffer(mems, pts=meta.get("pts"), duration=meta.get("duration"),
                  offset=meta.get("offset"))
