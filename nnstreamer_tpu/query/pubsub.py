"""mqttsink / mqttsrc — publish/subscribe streams over real MQTT 3.1.1.

Reference: gst/mqtt/ (mqttsink.c / mqttsrc.c, 3404 LoC): arbitrary Gst
streams ride MQTT PUBLISH messages whose payload is a fixed 1024-byte
``GstMQTTMessageHdr`` (num_mems, per-memory sizes, base/sent Unix epochs,
pts/dts/duration, caps string; mqttcommon.h:29-63) followed by the raw
memory bytes; publisher clocks are NTP-synced (ntputil.c) so subscribers on
other hosts can compute transit latency.

TPU-native build keeps that contract byte-for-byte (query/mqtt.py
``MessageHdr``) and speaks genuine MQTT 3.1.1 frames, so any standard
broker (mosquitto, EMQX, …) — or the built-in ``MqttBroker`` — carries the
stream, and an upstream nnstreamer subscriber can parse our header.

Elements:
  * ``mqttsink pub-topic=t host=… port=…`` — publishes every buffer;
    ``ntp-sync=true`` (+ ``ntp-host``/``ntp-port``) timestamps with an NTP
    epoch instead of the system clock; ``sparse=true`` ships each memory
    sparse-encoded under ``format=sparse`` caps (the reference's
    tensor_sparse link compression, §2.5 — pays off on mostly-zero
    tensors crossing slow links);
  * ``mqttsrc sub-topic=t`` — subscribes (MQTT wildcards ``+``/``#`` work)
    and re-emits buffers, recording ``mqtt_latency_us`` (receiver epoch −
    sender epoch) in buffer meta.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core.buffer import Buffer, TensorMemory
from ..core.log import logger
from ..core.types import Caps, TensorFormat
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.pipeline import SourceElement
from .mqtt import (
    MessageHdr,
    MqttBroker,
    MqttClient,
    get_epoch_us,
)

log = logger("pubsub")

#: backward-compatible alias (rounds 1-2 exposed the bespoke broker under
#: this name; it is now a real MQTT 3.1.1 broker)
PubSubBroker = MqttBroker


class EpochClock:
    """Per-element epoch source: one SNTP query at element start pins the
    offset between the NTP epoch and the local monotonic-ish system clock;
    per-buffer reads are then a local clock read plus the cached offset.
    (The reference also syncs once per connection, not per message —
    mqttsink.c via ntputil; querying NTP in the per-buffer hot path would
    cap FPS at the NTP RTT.)"""

    def __init__(self, ntp_hosts=None):
        self._offset_us = get_epoch_us(ntp_hosts) - time.time_ns() // 1000

    def now_us(self) -> int:
        return time.time_ns() // 1000 + self._offset_us


def _buffer_to_mqtt(buf: Buffer, base_epoch_us: int,
                    clock: EpochClock, sparse: bool = False,
                    stream_config: Optional[Any] = None) -> bytes:
    """Buffer → GstMQTTMessageHdr + raw (or sparse-encoded) memory bytes."""
    from ..core.types import TensorFormat as _TF
    from ..core.types import TensorsConfig
    from ..graph.parse import caps_to_gst_string

    config = buf.config or stream_config
    if config is None:  # static per-memory infos still describe the frame
        config = TensorsConfig(buf.tensors_info)
    if sparse:
        from ..elements.sparse import sparse_encode

        blobs = [sparse_encode(m.host(), m.info) for m in buf.memories]
        # keep the full stream config (dims/types/rate of the DENSE
        # tensors) and mark only the payload encoding as sparse
        caps = caps_to_gst_string(
            Caps.tensors(config).with_fields(format=_TF.SPARSE))
    else:
        blobs = [m.tobytes() for m in buf.memories]
        caps = caps_to_gst_string(Caps.tensors(config))
    hdr = MessageHdr(
        num_mems=len(blobs),
        size_mems=tuple(len(b) for b in blobs),
        base_time_epoch=base_epoch_us,
        sent_time_epoch=clock.now_us(),
        duration=buf.duration, dts=buf.dts, pts=buf.pts,
        caps_str=caps)
    return hdr.pack() + b"".join(blobs)


def _mqtt_to_buffer(payload: bytes,
                    recv_epoch_us: int) -> Buffer:
    """GstMQTTMessageHdr + raw memories → Buffer (config from caps_str)."""
    from ..graph.parse import parse_caps_string

    hdr = MessageHdr.unpack(payload)
    off = 1024
    config = None
    infos = None
    is_sparse = False
    if hdr.caps_str:
        try:
            caps = parse_caps_string(hdr.caps_str)
            if caps.media_type == "other/tensors":
                from ..core.types import TensorFormat as _TF

                is_sparse = caps.get("format") is _TF.SPARSE
                if caps.get("dims") is not None:
                    if is_sparse:  # dims/types describe the dense tensors
                        caps = caps.with_fields(format=_TF.STATIC)
                    config = caps.to_config()
                    infos = list(config.info)
        except (ValueError, KeyError):
            log.warning("unparsable caps in MQTT header: %r", hdr.caps_str)
    mems: List[TensorMemory] = []
    for i, size in enumerate(hdr.size_mems):
        blob = payload[off:off + size]
        if len(blob) != size:
            raise ValueError(
                f"MQTT payload truncated: memory {i} wants {size} bytes, "
                f"{len(blob)} left")
        off += size
        if is_sparse:
            from ..elements.sparse import sparse_decode

            arr, info = sparse_decode(bytes(blob))
            mems.append(TensorMemory(arr, info))
        elif infos is not None and i < len(infos):
            mems.append(TensorMemory.from_bytes(blob, infos[i]))
        else:
            mems.append(TensorMemory(np.frombuffer(
                bytearray(blob), np.uint8)))
    buf = Buffer(mems, pts=hdr.pts, dts=hdr.dts, duration=hdr.duration,
                 config=config)
    buf.meta["mqtt_latency_us"] = recv_epoch_us - hdr.sent_time_epoch
    buf.meta["mqtt_base_epoch_us"] = hdr.base_time_epoch
    return buf


def _parse_ntp_hosts(el: Any) -> Optional[Sequence[Tuple[str, int]]]:
    if not getattr(el, "ntp_sync", False):
        return None
    return [(str(el.ntp_host), int(el.ntp_port))]


@register_element
class MqttSink(Element):
    ELEMENT_NAME = "mqttsink"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 1883
        self.pub_topic = "nns/stream"
        self.client_id = ""
        self.keep_alive = 60
        self.ntp_sync = False
        self.ntp_host = "pool.ntp.org"
        self.ntp_port = 123
        self.sparse = False
        super().__init__(name, **props)
        self.add_sink_pad()
        self._client: Optional[MqttClient] = None
        self._base_epoch_us = 0
        self._clock: Optional[EpochClock] = None
        self._stream_config = None

    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        if caps.media_type == "other/tensors" \
                and caps.get("dims") is not None:
            # negotiated stream config rides the wire header even when
            # individual buffers don't carry one
            self._stream_config = caps.to_config()

    def start(self) -> None:
        cid = self.client_id or f"nns_tpu_sink_{id(self) & 0xFFFF:04x}"
        self._client = MqttClient(self.host, int(self.port), cid,
                                  int(self.keep_alive))
        self._clock = EpochClock(_parse_ntp_hosts(self))
        self._base_epoch_us = self._clock.now_us()

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        payload = _buffer_to_mqtt(buf, self._base_epoch_us, self._clock,
                                  sparse=bool(self.sparse),
                                  stream_config=self._stream_config)
        try:
            self._client.publish(self.pub_topic, payload)
        except OSError as e:
            log.error("mqttsink publish failed: %s", e)
            return FlowReturn.ERROR
        return FlowReturn.OK

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


@register_element
class MqttSrc(SourceElement):
    ELEMENT_NAME = "mqttsrc"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 1883
        self.sub_topic = "nns/stream"
        self.client_id = ""
        self.keep_alive = 60
        self.ntp_sync = False
        self.ntp_host = "pool.ntp.org"
        self.ntp_port = 123
        super().__init__(name, **props)
        self._client: Optional[MqttClient] = None
        self._clock: Optional[EpochClock] = None

    def negotiate(self) -> Caps:
        cid = self.client_id or f"nns_tpu_src_{id(self) & 0xFFFF:04x}"
        self._client = MqttClient(self.host, int(self.port), cid,
                                  int(self.keep_alive))
        self._client.subscribe(self.sub_topic)
        self._clock = EpochClock(_parse_ntp_hosts(self))
        return Caps.tensors(format=TensorFormat.FLEXIBLE)

    def create(self) -> Optional[Buffer]:
        while not self._stop_flag.is_set():
            try:
                got = self._client.recv_publish(timeout=0.2)
            except (ConnectionError, OSError):
                return None
            if got is None:
                continue
            _topic, payload = got
            try:
                return _mqtt_to_buffer(payload, self._clock.now_us())
            except Exception as e:  # noqa: BLE001 - untrusted network
                # input: a corrupt message (bad header, codes, or sparse
                # indices raising Index/KeyError deep in the codec) must
                # be dropped, never end the subscription
                log.warning("mqttsrc dropped malformed message: %s", e)
                continue
        return None

    def stop(self) -> None:
        super().stop()
        if self._client is not None:
            self._client.close()
            self._client = None
