"""Topic pub/sub stream transport (mqttsink/mqttsrc equivalents).

Reference: gst/mqtt/ (3404 LoC; paho-mqtt pub/sub of arbitrary Gst streams
with a fixed header carrying num_mems/sizes/timestamps + NTP epoch sync,
mqttcommon.h:29-63). paho isn't in this image, so the broker here is a
built-in topic-fanout TCP service (``PubSubBroker``); the elements keep the
reference's semantics:

  * ``mqttsink pub-topic=t``  — publishes every buffer (meta + payload + the
    publisher's wall-clock epoch, the ntputil analog);
  * ``mqttsrc sub-topic=t``   — subscribes and re-emits buffers, recording
    ``mqtt_latency_ns`` (receiver epoch − sender epoch) in buffer meta.

Wire: length-prefixed frames. SUB: {"op":"sub","topic":t}; PUB frames carry
{"op":"pub","topic":t,...buffer meta...} + payload.
"""

from __future__ import annotations

import json
import queue as _q
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.buffer import Buffer
from ..core.log import logger
from ..core.types import Caps, TensorFormat
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.pipeline import SourceElement
from .protocol import buffer_to_payload, payload_to_buffer

log = logger("pubsub")

_LEN = struct.Struct("<I")


def _send_frame(sock: socket.socket, meta: Dict[str, Any], payload: bytes = b"") -> None:
    meta_b = json.dumps(meta, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(meta_b)) + meta_b + _LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed")
        out += chunk
    return out


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (mlen,) = _LEN.unpack(_recv_exact(sock, 4))
    meta = json.loads(_recv_exact(sock, mlen) or b"{}")
    (plen,) = _LEN.unpack(_recv_exact(sock, 4))
    payload = _recv_exact(sock, plen) if plen else b""
    return meta, payload


class PubSubBroker:
    """Topic-fanout broker: publishers' frames are copied to every current
    subscriber of the topic (QoS-0 MQTT semantics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883):
        self._subs: Dict[str, List[socket.socket]] = {}
        self._lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(32)
        self._listener.settimeout(0.2)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PubSubBroker":
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="pubsub-broker")
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        subscribed: List[str] = []
        try:
            while not self._stop.is_set():
                meta, payload = _recv_frame(conn)
                op = meta.get("op")
                topic = str(meta.get("topic", ""))
                if op == "sub":
                    with self._lock:
                        self._subs.setdefault(topic, []).append(conn)
                    subscribed.append(topic)
                elif op == "pub":
                    with self._lock:
                        targets = list(self._subs.get(topic, []))
                    dead = []
                    for s in targets:
                        try:
                            _send_frame(s, meta, payload)
                        except OSError:
                            dead.append(s)
                    if dead:
                        with self._lock:
                            for s in dead:
                                for subs in self._subs.values():
                                    if s in subs:
                                        subs.remove(s)
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                for t in subscribed:
                    if conn in self._subs.get(t, []):
                        self._subs[t].remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


@register_element
class MqttSink(Element):
    ELEMENT_NAME = "mqttsink"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 1883
        self.pub_topic = "nns/stream"
        super().__init__(name, **props)
        self.add_sink_pad()
        self._sock: Optional[socket.socket] = None

    def start(self) -> None:
        self._sock = socket.create_connection((self.host, int(self.port)),
                                              timeout=5)

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        meta, payload = buffer_to_payload(buf)
        meta.update({"op": "pub", "topic": self.pub_topic,
                     "sent_epoch_ns": time.time_ns()})
        _send_frame(self._sock, meta, payload)
        return FlowReturn.OK

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


@register_element
class MqttSrc(SourceElement):
    ELEMENT_NAME = "mqttsrc"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 1883
        self.sub_topic = "nns/stream"
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None

    def negotiate(self) -> Caps:
        self._sock = socket.create_connection((self.host, int(self.port)),
                                              timeout=5)
        _send_frame(self._sock, {"op": "sub", "topic": self.sub_topic})
        self._sock.settimeout(0.2)
        return Caps.tensors(format=TensorFormat.FLEXIBLE)

    def create(self) -> Optional[Buffer]:
        while not self._stop_flag.is_set():
            try:
                meta, payload = _recv_frame(self._sock)
            except socket.timeout:
                continue
            except (ConnectionError, OSError):
                return None
            buf = payload_to_buffer(meta, payload)
            sent = meta.get("sent_epoch_ns")
            if sent is not None:
                buf.meta["mqtt_latency_ns"] = time.time_ns() - sent
            return buf
        return None

    def stop(self) -> None:
        super().stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
