"""gRPC tensor transport elements.

Reference: ext/nnstreamer/tensor_source/tensor_src_grpc + tensor_sink_grpc +
extra/nnstreamer_grpc_* (``service TensorService { rpc SendTensors(stream
Tensors); rpc RecvTensors(...) }``, nnstreamer.proto; either side may be the
gRPC server, blocking or async).

Implemented with grpcio's generic handlers (no codegen needed); method
``/nns.TensorService/SendTensors`` (client-streaming push). The message body
is selected by ``idl=``, mirroring the reference's two IDL builds
(nnstreamer_grpc_protobuf.cc / nnstreamer_grpc_flatbuf.cc):

  * ``idl=flex`` (default) — our wire meta-JSON + flex-tensor payload
    (query/protocol.py);
  * ``idl=protobuf`` — proto/tensors.proto messages (converters/protobuf_io);
  * ``idl=flatbuf`` — nnstreamer.fbs-layout FlatBuffers frames
    (converters/fb_io), byte-compatible with the reference schema.

Elements:

  * ``tensor_grpc_sink`` — client by default (streams buffers to a server),
    or ``server=true`` to serve RecvTensors pulls.
  * ``tensor_grpc_src``  — server by default (receives SendTensors pushes),
    or ``server=false`` to pull RecvTensors from a remote sink-server.
"""

from __future__ import annotations

import queue as _q
import struct
import threading
from typing import Any, Iterator, Optional

from ..core.buffer import Buffer
from ..core.log import logger
from ..core.types import Caps, TensorFormat, TensorsConfig, TensorsInfo
from ..graph.element import Element, FlowReturn, Pad, register_element
from ..graph.pipeline import SourceElement
from .protocol import buffer_to_payload, payload_to_buffer

log = logger("grpc")

SEND_METHOD = "/nns.TensorService/SendTensors"
RECV_METHOD = "/nns.TensorService/RecvTensors"


def _encode_flex(buf: Buffer) -> bytes:
    import json

    meta, payload = buffer_to_payload(buf)
    meta_b = json.dumps(meta, separators=(",", ":")).encode()
    return struct.pack("<I", len(meta_b)) + meta_b + payload


def _decode_flex(raw: bytes) -> Buffer:
    import json

    (mlen,) = struct.unpack_from("<I", raw)
    meta = json.loads(raw[4:4 + mlen])
    return payload_to_buffer(meta, raw[4 + mlen:])


def _codec(idl: str):
    """(encode, decode) pair for an IDL name."""
    idl = (idl or "flex").lower()
    if idl == "flex":
        return _encode_flex, _decode_flex
    if idl == "protobuf":
        from ..converters.protobuf_io import frame_to_proto, proto_to_frame

        return frame_to_proto, proto_to_frame
    if idl == "flatbuf":
        from ..converters.fb_io import flatbuf_to_frame, frame_to_flatbuf

        def enc(buf: Buffer) -> bytes:
            return frame_to_flatbuf(buf, buf.config)

        def dec(raw: bytes) -> Buffer:
            return flatbuf_to_frame(raw)[0]

        return enc, dec
    raise ValueError(f"grpc: unknown idl {idl!r} (flex/protobuf/flatbuf)")


@register_element
class TensorGrpcSrc(SourceElement):
    ELEMENT_NAME = "tensor_grpc_src"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 55115
        self.server = True
        self.idl = "flex"
        super().__init__(name, **props)
        self._encode, self._decode = _codec(self.idl)
        self._inbox: "_q.Queue[Buffer]" = _q.Queue(maxsize=64)
        self._grpc_server = None

    def negotiate(self) -> Caps:
        if self.server:
            self._start_server()
        else:
            self._start_pull_client()
        return Caps.tensors(format=TensorFormat.FLEXIBLE)

    def _start_server(self) -> None:
        import grpc

        element = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method == SEND_METHOD:
                    def send_tensors(request_iterator, context):
                        for raw in request_iterator:
                            element._inbox.put(element._decode(raw))
                        return b""

                    return grpc.stream_unary_rpc_method_handler(
                        send_tensors,
                        request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                return None

        from concurrent import futures

        self._grpc_server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._grpc_server.add_generic_rpc_handlers((Handler(),))
        self.bound_port = self._grpc_server.add_insecure_port(
            f"{self.host}:{int(self.port)}")
        self._grpc_server.start()

    def _start_pull_client(self) -> None:
        import grpc

        channel = grpc.insecure_channel(f"{self.host}:{int(self.port)}")
        stream = channel.unary_stream(
            RECV_METHOD, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

        def pull() -> None:
            try:
                for raw in stream(b""):
                    self._inbox.put(self._decode(raw))
            except grpc.RpcError as e:
                log.warning("grpc pull ended: %s", e)

        threading.Thread(target=pull, daemon=True,
                         name=f"grpc-pull:{self.name}").start()

    def create(self) -> Optional[Buffer]:
        while not self._stop_flag.is_set():
            try:
                return self._inbox.get(timeout=0.1)
            except _q.Empty:
                continue
        return None

    def stop(self) -> None:
        super().stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=0.5)
            self._grpc_server = None


@register_element
class TensorGrpcSink(Element):
    ELEMENT_NAME = "tensor_grpc_sink"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 55115
        self.server = False
        self.idl = "flex"
        super().__init__(name, **props)
        self._encode, self._decode = _codec(self.idl)
        self.add_sink_pad(template=Caps.any_tensors())
        self._outq: "_q.Queue[Optional[bytes]]" = _q.Queue(maxsize=64)
        self._call_thread: Optional[threading.Thread] = None
        self._grpc_server = None

    def start(self) -> None:
        import grpc

        if self.server:
            element = self

            class Handler(grpc.GenericRpcHandler):
                def service(self, handler_call_details):
                    if handler_call_details.method == RECV_METHOD:
                        def recv_tensors(request, context) -> Iterator[bytes]:
                            while True:
                                item = element._outq.get()
                                if item is None:
                                    return
                                yield item

                        return grpc.unary_stream_rpc_method_handler(
                            recv_tensors,
                            request_deserializer=lambda b: b,
                            response_serializer=lambda b: b)
                    return None

            from concurrent import futures

            self._grpc_server = grpc.server(
                futures.ThreadPoolExecutor(max_workers=4))
            self._grpc_server.add_generic_rpc_handlers((Handler(),))
            self.bound_port = self._grpc_server.add_insecure_port(
                f"{self.host}:{int(self.port)}")
            self._grpc_server.start()
            return

        channel = grpc.insecure_channel(f"{self.host}:{int(self.port)}")
        stream_call = channel.stream_unary(
            SEND_METHOD, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

        def run_call() -> None:
            def gen() -> Iterator[bytes]:
                while True:
                    item = self._outq.get()
                    if item is None:
                        return
                    yield item

            try:
                stream_call(gen())
            except grpc.RpcError as e:
                self.post_error(f"grpc send failed: {e.code()}")

        self._call_thread = threading.Thread(target=run_call, daemon=True,
                                             name=f"grpc-send:{self.name}")
        self._call_thread.start()

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        self._outq.put(self._encode(buf))
        return FlowReturn.OK

    def stop(self) -> None:
        self._outq.put(None)
        if self._call_thread is not None:
            self._call_thread.join(timeout=5)
            self._call_thread = None
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=0.5)
            self._grpc_server = None
