"""query.router — health-routed multi-backend offload.

The query client (client.py) talks to exactly one ``tensor_query``
server: one dead backend means degraded-local-fallback for the whole
pipeline. This module turns that point-to-point link into a routed
fleet — a :class:`BackendSet` of N servers behind one
:class:`QueryRouter` that keeps serving through backend loss:

* **Placement** is least-loaded-of-two-random-choices ("power of two
  choices"): draw two distinct healthy backends, dispatch to the less
  loaded. Load is the obs.fleet aggregator's per-instance
  queue-depth/readiness snapshot (``FleetAggregator.routing_view``)
  when an aggregator is attached — the data PR 4 already put on the
  wire, used for placement instead of dashboards — falling back to
  locally observed in-flight counts + EWMA latency otherwise.
* **Per-backend isolation.** Every backend owns its connection, its
  :class:`resilience.policy.CircuitBreaker` (named
  ``query:<router>:<host:port>`` so the state gauge separates
  backends), and draws dial/resend attempts from the request's one
  shared :class:`RetryBudget` — the no-retry² rule, per fleet.
* **Mid-stream failover.** A buffer whose backend dies mid-request is
  transparently re-dispatched to a healthy peer under its ORIGINAL
  deadline (``router.failover`` event + counter); the dead backend's
  breaker opens and the router stops placing there until its
  half-open probe succeeds.
* **Hedged dispatch** (``hedge_ms > 0``): a latency-critical buffer
  gets a second send to a different backend once the observed P95
  round-trip (floored at ``hedge_ms``) elapses without a response;
  first result wins, the loser's round trip completes in the
  background and is discarded (its connection stays in protocol sync)
  — "The Tail at Scale" hedging against outliers.
* **Session affinity.** ``buf.meta["session"]`` consistent-hashes
  onto the ring (stable under backend add/remove) so multi-turn LM
  requests land where their paged prefix cache lives; a dead
  affinity target spills to two-choice placement with an explicit
  ``router.spill`` event.
* **Live add/remove + graceful drain** — the autoscaling primitive:
  :meth:`BackendSet.add` / :meth:`remove`; draining a backend stops
  new placements, lets in-flight requests finish, then closes.
* **Deadline-aware admission**: an expired buffer is shed at the
  router door (``resilience.shed`` site="router"), never dispatched.

The router raises :class:`RouterError` only when every backend is
down and the budget is spent; the hosting client then takes its
existing ``fallback=`` path (health DEGRADED, not pipeline error).

Zero-overhead contract: a client without ``backends=`` never
constructs a router — the per-buffer cost is one attribute is-None
check in ``chain()``, the same contract as the chaos hooks.
"""

from __future__ import annotations

import hashlib
import random
import socket
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import tune as _tune
from ..core.log import logger
from ..obs import events as _events
from ..obs import fleet as _fleet
from ..obs import metrics as _obs
from ..obs import slo as _slo
from ..obs import tracing as _tracing
from ..resilience import policy as _rp
from .protocol import (
    Cmd,
    QueryProtocolError,
    recv_message,
    send_message,
)

log = logger("query")

__all__ = ["Backend", "BackendSet", "QueryRouter", "RouterError",
           "parse_endpoints"]

#: backend lifecycle states (the ``nnstpu_router_backend_state`` gauge
#: mirrors them: 0=active, 1=draining, 2=closed)
ACTIVE = "active"
DRAINING = "draining"
CLOSED = "closed"
_STATE_CODE = {ACTIVE: 0, DRAINING: 1, CLOSED: 2}

#: bound on the session pin/owner tables (LRU-evicted) — placement
#: state, not correctness state: an evicted session just re-places
#: through the affinity ring on its next buffer
SESSION_PIN_LIMIT = 4096

#: virtual nodes per backend on the affinity hash ring — enough spread
#: that removing one backend of N only remaps ~1/N of the sessions
RING_VNODES = 32

#: EWMA smoothing for per-backend round-trip latency
EWMA_ALPHA = 0.2

#: bounded reservoir of recent round trips feeding the hedge P95
LATENCY_WINDOW = 128


class RouterError(ConnectionError):
    """Every routable backend refused/failed and the retry budget is
    spent — the caller's last resort (local fallback) takes over."""


def parse_endpoints(spec: Any) -> List[Tuple[str, int]]:
    """``"host:port,host:port"`` (or a list of such strings) into
    [(host, port)] — validated, deduplicated, order-preserving."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",")]
    else:
        parts = [str(p).strip() for p in spec]
    out: List[Tuple[str, int]] = []
    seen = set()
    for p in parts:
        if not p:
            continue
        host, sep, port_s = p.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"backend {p!r} must be host:port")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(f"backend {p!r} has a non-integer port")
        if not 0 < port < 65536:
            raise ValueError(f"backend {p!r} port out of range")
        key = (host, port)
        if key in seen:
            raise ValueError(f"backend {p!r} listed twice")
        seen.add(key)
        out.append(key)
    return out


# --------------------------------------------------------------------------- #
# Backend: one server endpoint with its own connection + breaker
# --------------------------------------------------------------------------- #

class Backend:
    """One ``tensor_query`` server endpoint.

    Owns a lazily dialed connection (serial request/response under
    ``_wire_lock`` — concurrency across the fleet comes from different
    backends proceeding in parallel, e.g. a hedge), a circuit breaker,
    and the local load signals (in-flight count, EWMA latency) used
    when no fleet aggregator is attached. ``instance`` is the server's
    advertised obs.fleet instance id (INFO_APPROVE handshake), joining
    this endpoint to its fleet snapshot for routed placement.
    """

    def __init__(self, host: str, port: int, owner: str,
                 timeout_s: float = 10.0, breaker_threshold: int = 5,
                 breaker_reset_s: float = 5.0):
        self.host = host
        self.port = int(port)
        self.endpoint = f"{host}:{port}"
        self.owner = owner
        self.timeout_s = float(timeout_s)
        self.state = ACTIVE
        self.instance: Optional[str] = None  # fleet id, learned on dial
        self.breaker = _rp.CircuitBreaker(
            _rp.backend_breaker_name(owner, self.endpoint),
            failure_threshold=int(breaker_threshold),
            reset_s=float(breaker_reset_s))
        self._sock: Optional[socket.socket] = None
        #: serializes the request/response exchange on this connection
        self._wire_lock = threading.Lock()
        #: guards state/in-flight bookkeeping (never held across I/O)
        self._lock = threading.Lock()
        self.inflight = 0
        self.ewma_s: Optional[float] = None
        self.dispatched = 0

    # -- connection ------------------------------------------------------- #
    def _connect(self, caps: str) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_message(sock, Cmd.INFO_REQ, {"caps": caps})
            cmd, meta, _ = recv_message(sock)
            if cmd is Cmd.INFO_DENY:
                raise ConnectionError(
                    f"{self.endpoint}: server denied connection: "
                    f"{meta.get('error', meta)}")
            if cmd is not Cmd.INFO_APPROVE:
                raise ConnectionError(
                    f"{self.endpoint}: unexpected handshake reply "
                    f"{cmd}: {meta}")
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        inst = meta.get("instance")
        self.instance = str(inst) if inst else None
        _events.record("router.connect",
                       f"{self.owner}: connected backend {self.endpoint}"
                       + (f" (instance {self.instance})"
                          if self.instance else ""),
                       element=self.owner, backend=self.endpoint)
        return sock

    def request(self, meta: Dict[str, Any], payload: bytes,
                caps: str) -> Tuple[Dict[str, Any], bytes]:
        """One synchronous round trip on this backend's connection.
        Raises ConnectionError/OSError/QueryProtocolError on failure
        (the connection is dropped so the next attempt dials fresh);
        breaker and load-signal accounting happen here so every caller
        — primary, failover, hedge — feeds the same placement state."""
        with self._lock:
            if self.state == CLOSED:
                raise ConnectionError(f"{self.endpoint}: backend closed")
            self.inflight += 1
        t0 = time.monotonic()
        try:
            with self._wire_lock:
                if self._sock is None:
                    self._sock = self._connect(caps)
                sock = self._sock
                try:
                    send_message(sock, Cmd.DATA, meta, payload)
                    cmd, rmeta, rpayload = recv_message(sock)
                except BaseException:
                    self._drop_conn()
                    raise
                if cmd is Cmd.ERROR:
                    self._drop_conn()
                    raise QueryProtocolError(
                        rmeta.get("error", "server error"))
                if cmd is not Cmd.RESULT:
                    self._drop_conn()
                    raise QueryProtocolError(f"unexpected reply {cmd}")
            rtt = time.monotonic() - t0
            with self._lock:
                self.ewma_s = rtt if self.ewma_s is None else \
                    (1 - EWMA_ALPHA) * self.ewma_s + EWMA_ALPHA * rtt
                self.dispatched += 1
            self.breaker.record_success()
            return rmeta, rpayload
        except (ConnectionError, OSError, QueryProtocolError):
            self.breaker.record_failure()
            raise
        finally:
            with self._lock:
                self.inflight -= 1

    def _drop_conn(self) -> None:
        """Close the socket (wire lock held by the caller) so the next
        request dials fresh — a half-consumed exchange is never reused."""
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def ensure_connected(self, caps: str) -> None:
        """Dial + INFO handshake without sending a request, so the
        fleet ``instance`` id is learned up front — prefix-aware
        placement joins digests to endpoints through it, and a backend
        that never dispatched would otherwise stay anonymous."""
        with self._wire_lock:
            if self._sock is None:
                self._sock = self._connect(caps)

    def local_load(self) -> float:
        """Load score from locally observed signals: requests in flight
        weighted by how slow this backend has been lately."""
        with self._lock:
            lat = self.ewma_s if self.ewma_s is not None else 0.0
            return self.inflight * (1.0 + lat)

    def close(self) -> None:
        with self._lock:
            self.state = CLOSED
        with self._wire_lock:
            self._drop_conn()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Backend({self.endpoint}, {self.state})"


# --------------------------------------------------------------------------- #
# BackendSet: membership, affinity ring, two-choice placement
# --------------------------------------------------------------------------- #

def _ring_hash(key: str) -> int:
    """Stable 64-bit hash (NOT Python's salted ``hash``) so affinity
    survives process restarts and is identical across hosts."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big")


class BackendSet:
    """The router's membership view: live add/remove, graceful drain,
    the consistent-hash affinity ring, and two-random-choice placement
    fed by fleet or local load signals."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]], owner: str,
                 timeout_s: float = 10.0, breaker_threshold: int = 5,
                 breaker_reset_s: float = 5.0,
                 rng: Optional[random.Random] = None):
        self.owner = owner
        self._timeout_s = float(timeout_s)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        self._lock = threading.Lock()
        self._backends: Dict[str, Backend] = {}  # guarded-by: _lock
        self._ring: List[Tuple[int, str]] = []  # guarded-by: _lock
        # session placement state (both guarded-by: _lock, LRU-bounded):
        # _pins are explicit re-homes (migration / eager drain re-pin)
        # consulted BEFORE the ring; _owners is the observed last
        # successful placement, which is what drain enumerates
        self._pins: "OrderedDict[str, str]" = OrderedDict()
        self._owners: "OrderedDict[str, str]" = OrderedDict()
        self._rng = rng if rng is not None else random.Random()
        for host, port in endpoints:
            self.add(f"{host}:{port}")
        if not self._backends:
            raise ValueError("BackendSet needs at least one backend")

    # -- membership ------------------------------------------------------- #
    def add(self, endpoint: str) -> Backend:
        """Live add (the autoscaling scale-up primitive): the backend
        joins the ring and becomes placeable immediately."""
        (host, port), = parse_endpoints(endpoint)
        ep = f"{host}:{port}"
        with self._lock:
            if ep in self._backends:
                raise ValueError(f"backend {ep} already in the set")
            be = Backend(host, port, self.owner,
                         timeout_s=self._timeout_s,
                         breaker_threshold=self._breaker_threshold,
                         breaker_reset_s=self._breaker_reset_s)
            self._backends[ep] = be
            self._rebuild_ring()
        _events.record("router.backend_add",
                       f"{self.owner}: backend {ep} added",
                       element=self.owner, backend=ep)
        return be

    def drain(self, endpoint: str) -> Backend:
        """Graceful drain: stop placing on the backend, leave its
        in-flight requests to finish. :meth:`reap_drained` (called on
        every dispatch) closes it once idle — scale-down without
        dropping a single buffer. Sessions the backend owns are
        re-pinned EAGERLY here, so the first post-drain buffer dials
        its new home directly instead of paying a lazy failover round
        trip."""
        with self._lock:
            be = self._backends.get(endpoint)
            if be is None:
                raise KeyError(f"no backend {endpoint}")
            with be._lock:
                be.state = DRAINING
            self._rebuild_ring()
        _events.record("router.drain",
                       f"{self.owner}: backend {endpoint} draining "
                       f"({be.inflight} in flight)",
                       element=self.owner, backend=endpoint)
        self._repin_sessions(endpoint)
        self.reap_drained()
        return be

    def remove(self, endpoint: str, drain: bool = True) -> None:
        """Live remove: with ``drain=True`` (default) in-flight work
        finishes first; ``drain=False`` severs immediately (in-flight
        requests on it fail over via the normal dispatch loop)."""
        if drain:
            be = self.drain(endpoint)
            deadline = time.monotonic() + be.timeout_s
            while be.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        with self._lock:
            be = self._backends.pop(endpoint, None)
            self._rebuild_ring()
            # drop placement state naming the gone backend (drain
            # already re-pinned; this covers the drain=False sever)
            for table in (self._pins, self._owners):
                for s in [s for s, ep in table.items() if ep == endpoint]:
                    del table[s]
        if be is not None:
            be.close()
            _events.record("router.backend_remove",
                           f"{self.owner}: backend {endpoint} removed",
                           element=self.owner, backend=endpoint)

    def reap_drained(self) -> None:
        """Close any draining backend whose in-flight count hit zero."""
        with self._lock:
            done = [be for be in self._backends.values()
                    if be.state == DRAINING and be.inflight == 0]
        for be in done:
            be.close()
            _events.record("router.backend_closed",
                           f"{self.owner}: drained backend {be.endpoint} "
                           f"closed", element=self.owner,
                           backend=be.endpoint)

    def _rebuild_ring(self) -> None:  # guarded-by: _lock
        """Affinity ring over ACTIVE backends (draining/closed members
        take no new sessions). Caller holds ``_lock``."""
        ring: List[Tuple[int, str]] = []
        for ep, be in self._backends.items():
            if be.state != ACTIVE:
                continue
            for v in range(RING_VNODES):
                ring.append((_ring_hash(f"{ep}#{v}"), ep))
        ring.sort()
        self._ring = ring

    def backends(self) -> List[Backend]:
        with self._lock:
            return list(self._backends.values())

    def get(self, endpoint: str) -> Optional[Backend]:
        with self._lock:
            return self._backends.get(endpoint)

    # -- session placement state ------------------------------------------- #
    def pin_session(self, session: str, endpoint: str) -> None:
        """Explicitly re-home a session (migration / drain hand-off):
        :meth:`_affinity` honors the pin before the ring, so the next
        buffer dials ``endpoint`` directly."""
        with self._lock:
            self._pins[session] = endpoint
            self._pins.move_to_end(session)
            self._owners[session] = endpoint
            self._owners.move_to_end(session)
            self._trim_session_tables()

    def unpin_session(self, session: str) -> None:
        with self._lock:
            self._pins.pop(session, None)

    def note_session(self, session: str, endpoint: str) -> None:
        """Record where a session's buffer actually landed (dispatch
        success path). Keeps the ownership census current and makes an
        existing pin track reality after a failover moved the session."""
        with self._lock:
            self._owners[session] = endpoint
            self._owners.move_to_end(session)
            if session in self._pins and self._pins[session] != endpoint:
                self._pins[session] = endpoint
                self._pins.move_to_end(session)
            self._trim_session_tables()

    def sessions_owned(self, endpoint: str) -> List[str]:
        """Sessions currently homed on ``endpoint`` (observed placement
        union explicit pins) — the drain/migration census."""
        with self._lock:
            return sorted(
                {s for s, ep in self._owners.items() if ep == endpoint}
                | {s for s, ep in self._pins.items() if ep == endpoint})

    def _trim_session_tables(self) -> None:  # guarded-by: _lock
        while len(self._pins) > SESSION_PIN_LIMIT:
            self._pins.popitem(last=False)
        while len(self._owners) > SESSION_PIN_LIMIT:
            self._owners.popitem(last=False)

    def _repin_sessions(self, endpoint: str) -> int:
        """Eagerly re-home every session owned by a draining backend
        (the ring already excludes it). Each session re-places through
        the normal :meth:`pick` path — deterministic ring hash first —
        and lands as an explicit pin."""
        moved = 0
        for s in self.sessions_owned(endpoint):
            be = self.pick(session=s, exclude=frozenset({endpoint}))
            if be is None:
                continue
            self.pin_session(s, be.endpoint)
            moved += 1
        if moved:
            _events.record(
                "router.repin",
                f"{self.owner}: {moved} session(s) eagerly re-pinned "
                f"off draining {endpoint}",
                element=self.owner, backend=endpoint, sessions=moved)
        return moved

    def repin_dead_owner(self, endpoint: str) -> List[Tuple[str, str]]:
        """Crash re-pin (fleet/checkpoint restore): the owner died
        WITHOUT a drain — no export round trip happened — so re-home
        every session it owned onto survivors and return the
        ``(session, new_endpoint)`` map the checkpoint splice needs.
        Must run BEFORE :meth:`remove`, which drops the ownership
        census this reads."""
        moved: List[Tuple[str, str]] = []
        for s in self.sessions_owned(endpoint):
            be = self.pick(session=s, exclude=frozenset({endpoint}))
            if be is None:
                continue
            self.pin_session(s, be.endpoint)
            moved.append((s, be.endpoint))
        if moved:
            _events.record(
                "router.repin_dead",
                f"{self.owner}: {len(moved)} session(s) re-pinned off "
                f"dead owner {endpoint}",
                severity="warning", element=self.owner, backend=endpoint,
                sessions=len(moved))
        return moved

    def __len__(self) -> int:
        with self._lock:
            return len(self._backends)

    # -- load signals ------------------------------------------------------ #
    def _fleet_load(self, be: Backend) -> Optional[float]:
        """Queue depth from the attached aggregator's routing view, or
        None when no view covers this backend (unknown instance, no
        aggregator, instance not yet pushed)."""
        agg = _fleet.aggregator()
        if agg is None or be.instance is None:
            return None
        view = agg.routing_view().get(be.instance)
        if view is None:
            return None
        if not view["routable"]:
            return float("inf")  # stale/not-ready: last-choice only
        return float(view["queue_depth"])

    def _load(self, be: Backend) -> float:
        fleet = self._fleet_load(be)
        if fleet is not None:
            # tiebreak equal fleet depths with the local signal so two
            # idle backends still spread instead of pile-on
            return fleet * 1e3 + be.local_load()
        return be.local_load()

    # -- placement --------------------------------------------------------- #
    def _routable(self, exclude: frozenset) -> List[Backend]:
        with self._lock:
            cands = [be for be in self._backends.values()
                     if be.state == ACTIVE and be.endpoint not in exclude]
        # non-consuming gate: `state` transitions an elapsed cooldown to
        # half-open WITHOUT spending the probe quota. allow() is called
        # only on the backend actually selected (see pick) — calling it
        # here would burn the half-open probe on every candidate scan
        # and strand recovering backends in half-open forever
        return [be for be in cands if be.breaker.state != _rp.OPEN]

    def pick(self, session: Optional[str] = None,
             exclude: frozenset = frozenset(),
             prefix_hashes: Optional[Sequence[str]] = None
             ) -> Optional[Backend]:
        """Choose a backend: session affinity first (consistent hash,
        spilling with an event when the target is unroutable), then the
        backend advertising the longest shared KV prefix
        (``prefix_hashes`` probed against the fleet digest —
        serving.disagg placement), else
        least-loaded-of-two-random-choices. None when nothing routable
        remains — the caller's fallback decision point. Selection is a
        commitment: the winner's breaker admission (the half-open probe
        quota) is consumed here, never for losing candidates."""
        if session is not None:
            be = self._affinity(session, exclude)
            if be is not None:
                return be
        if prefix_hashes:
            be = self._prefix_match(prefix_hashes, exclude)
            if be is not None:
                return be
        cands = self._routable(exclude)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0] if cands[0].breaker.allow() else None
        a, b = self._rng.sample(cands, 2)
        first, second = (a, b) if self._load(a) <= self._load(b) \
            else (b, a)
        if first.breaker.allow():
            return first
        if second.breaker.allow():
            return second
        return None

    def _prefix_match(self, hashes: Sequence[str],
                      exclude: frozenset) -> Optional[Backend]:
        """The backend whose fleet digest holds the request's longest
        leading prefix (FleetAggregator.longest_prefix) — a prefix hit
        over the wire beats a least-loaded placement that would
        re-prefill from token zero. None when no aggregator is
        attached, no instance advertises the prefix, or the holder is
        not in this set / not admissible; the caller falls through to
        two-choice."""
        agg = _fleet.aggregator()
        if agg is None:
            return None
        inst, depth = agg.longest_prefix(hashes)
        if inst is None or depth <= 0:
            return None
        with self._lock:
            cands = [be for be in self._backends.values()
                     if be.state == ACTIVE and be.instance == inst
                     and be.endpoint not in exclude]
        for be in cands:
            if be.breaker.state != _rp.OPEN and be.breaker.allow():
                _PREFIX_PLACED.labels(self.owner).inc()
                _events.record(
                    "router.prefix_place",
                    f"{self.owner}: placed on {be.endpoint} holding "
                    f"{depth} shared KV prefix page(s)",
                    element=self.owner, backend=be.endpoint, depth=depth)
                return be
        return None

    def _affinity(self, session: str,
                  exclude: frozenset) -> Optional[Backend]:
        # explicit pins (migration / drain re-pin) outrank the ring:
        # the pinned backend holds the session's migrated KV pages
        with self._lock:
            pinned = self._pins.get(session)
        if pinned is not None:
            be = self.get(pinned)
            if be is not None and be.state == ACTIVE \
                    and pinned not in exclude and be.breaker.allow():
                return be
            # pinned home unroutable (dead, draining, or excluded by a
            # failed attempt): the pin is stale — drop it and let the
            # ring/two-choice place the session fresh
            with self._lock:
                self._pins.pop(session, None)
        with self._lock:
            ring = self._ring
        if not ring:
            return None
        h = _ring_hash(session)
        # first vnode clockwise of the session's point
        lo, hi = 0, len(ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        ep = ring[lo % len(ring)][1]
        be = self.get(ep)
        if be is not None and be.state == ACTIVE \
                and ep not in exclude and be.breaker.allow():
            return be
        # the session's home is dead/draining/excluded: spill — the
        # remote prefix cache there is lost; say so explicitly
        _events.record("router.spill",
                       f"{self.owner}: session affinity target {ep} "
                       f"unroutable — spilling to two-choice placement",
                       severity="warning", element=self.owner, backend=ep)
        return None

    def close(self) -> None:
        for be in self.backends():
            be.close()


# --------------------------------------------------------------------------- #
# QueryRouter: dispatch with failover + hedging
# --------------------------------------------------------------------------- #

#: router telemetry — registered here (query/router.py owns the
#: ``router`` metric layer; check_metric_names.py pins that). The
#: ``backend`` label is host:port endpoints from the configured set:
#: cardinality bounded by fleet size, never by request volume.
_reg = _obs.registry()
_DISPATCH_TOTAL = _reg.counter(
    "nnstpu_router_dispatch_total",
    "Buffers dispatched by the query router, by backend",
    ("element", "backend"))
_FAILOVER_TOTAL = _reg.counter(
    "nnstpu_router_failover_total",
    "Buffers re-dispatched to a peer after their backend failed"
    " mid-request", ("element",))
_RTT = _reg.histogram(
    "nnstpu_router_roundtrip_seconds",
    "Routed request round-trip latency (winning attempt)",
    ("element",))
_BACKEND_STATE = _reg.gauge(
    "nnstpu_router_backend_state",
    "Backend lifecycle per router (0=active, 1=draining, 2=closed)",
    ("element", "backend"))
_INFLIGHT = _reg.gauge(
    "nnstpu_router_inflight_depth",
    "Requests in flight per backend", ("element", "backend"))
_PREFIX_PLACED = _reg.counter(
    "nnstpu_router_prefix_placed_total",
    "Dispatches placed on the backend advertising the longest shared"
    " KV prefix (serving.disagg prefix-aware routing)", ("element",))


class QueryRouter:
    """Spreads one client's offload across a :class:`BackendSet`.

    ``dispatch`` is the whole contract: one (meta, payload) request in,
    one (rmeta, rpayload) result out, surviving backend loss by
    failover and (optionally) hedging the tail. ``hedge_ms`` <= 0
    disables hedging; > 0 arms it with that floor under the live P95.
    """

    def __init__(self, backends: BackendSet, name: str,
                 max_request_retry: int = 3, hedge_ms: float = 0.0,
                 retry_policy: Optional[_rp.RetryPolicy] = None):
        self.backends = backends
        self.name = name
        self.max_request_retry = max(int(max_request_retry), 1)
        self.hedge_ms = float(hedge_ms)
        self._retry = retry_policy if retry_policy is not None \
            else _rp.RetryPolicy()
        #: set by the hosting client during its EOS drain: membership
        #: growth is refused while draining (a backend added mid-drain
        #: could never owe the drain a result)
        self.draining = False
        self._lat_lock = threading.Lock()
        self._latencies: List[float] = []
        self._caps: Callable[[], str] = lambda: ""
        ref = weakref.ref(self)
        for be in backends.backends():
            self._register_gauges(ref, be.endpoint)
        _live_routers.add(self)

    def _register_gauges(self, ref, endpoint: str) -> None:
        _BACKEND_STATE.labels(self.name, endpoint).set_function(
            lambda: (lambda r: 0 if r is None or
                     r.backends.get(endpoint) is None
                     else _STATE_CODE[r.backends.get(endpoint).state])(
                         ref()))
        _INFLIGHT.labels(self.name, endpoint).set_function(
            lambda: (lambda r: 0 if r is None or
                     r.backends.get(endpoint) is None
                     else r.backends.get(endpoint).inflight)(ref()))

    def set_caps_provider(self, fn: Callable[[], str]) -> None:
        """The handshake caps string, provided lazily — negotiation may
        not have happened when the router is constructed."""
        self._caps = fn

    def prime(self) -> int:
        """Dial every ACTIVE backend once (handshake only) so each
        learns its fleet instance id before the first dispatch —
        prefix-aware placement needs the endpoint-to-instance join.
        Unreachable backends are skipped (their breakers record the
        failure); returns how many backends are now identified."""
        caps = self._caps()
        n = 0
        for be in self.backends.backends():
            if be.state != ACTIVE:
                continue
            if be.instance is None:
                try:
                    be.ensure_connected(caps)
                except (ConnectionError, OSError, QueryProtocolError):
                    be.breaker.record_failure()
                    continue
            n += be.instance is not None
        return n

    def choose(self, session: Optional[str] = None,
               prefix_hashes: Optional[Sequence[str]] = None
               ) -> Optional[Backend]:
        """Placement WITHOUT dispatch: the backend :meth:`dispatch`
        would pick right now (affinity -> prefix digest -> two-choice).
        serving.disagg uses it to choose the decode target before the
        prefill even runs, so pages stream to where the request will
        land. The choice is advisory — the later dispatch re-picks
        unless pinned via ``prefer=``."""
        return self.backends.pick(session=session,
                                  prefix_hashes=prefix_hashes)

    # -- membership passthrough (gauges track new members) ----------------- #
    def add_backend(self, endpoint: str) -> Backend:
        import weakref

        if self.draining:
            raise RuntimeError(
                f"{self.name}: draining — refusing to add backend "
                f"{endpoint}")
        be = self.backends.add(endpoint)
        self._register_gauges(weakref.ref(self), be.endpoint)
        return be

    def remove_backend(self, endpoint: str, drain: bool = True) -> None:
        self.backends.remove(endpoint, drain=drain)

    def drain_backend(self, endpoint: str) -> Backend:
        return self.backends.drain(endpoint)

    # -- hedging ----------------------------------------------------------- #
    def _observe_latency(self, rtt: float) -> None:
        with self._lat_lock:
            self._latencies.append(rtt)
            if len(self._latencies) > LATENCY_WINDOW:
                del self._latencies[:len(self._latencies)
                                    - LATENCY_WINDOW]

    def hedge_delay_s(self) -> float:
        """Observed P95 round trip, floored at ``hedge_ms`` — hedge
        only requests already slower than ~19 of 20 peers, never
        earlier than the configured floor."""
        floor = self.hedge_ms / 1e3
        with self._lat_lock:
            lats = sorted(self._latencies)
        if len(lats) < 20:
            return floor
        return max(floor, lats[int(len(lats) * 0.95)])

    # -- dispatch ----------------------------------------------------------- #
    def dispatch(self, meta: Dict[str, Any], payload: bytes,
                 deadline: Optional[_rp.Deadline] = None,
                 session: Optional[str] = None,
                 prefix_hashes: Optional[Sequence[str]] = None,
                 prefer: Optional[str] = None
                 ) -> Tuple[Dict[str, Any], bytes]:
        """Route one request. Raises :class:`RouterError` once every
        routable backend has failed it and the shared retry budget is
        spent; raises nothing for a single backend death — that is the
        failover path, not an error.

        ``prefix_hashes`` (kv_cache.prompt_path_hashes) turns on
        prefix-cache-aware placement; ``prefer`` pins the first attempt
        to a specific endpoint when it is still routable (serving.disagg
        sends the decode request to the backend it just streamed pages
        to) — on failure the normal failover loop takes over."""
        budget = _rp.RetryBudget(self.max_request_retry, site="router")
        tried: set = set()
        used_backend = False  # at least one real attempt hit a wire
        last: Optional[Exception] = None
        attempt = 0
        span = _tracing.start_span(
            "router.dispatch", parent=_tracing.current_context(),
            attrs={"element": self.name})
        try:
            while budget.take():
                if deadline is not None and deadline.expired():
                    _rp.record_shed(
                        "router",
                        f"{self.name}: deadline expired after "
                        f"{attempt} attempt(s)", element=self.name)
                    raise _ShedSignal()
                # exclude backends that already failed THIS buffer so a
                # failover lands on a peer; once every peer has been
                # tried, clear the exclusion and let backoff + breaker
                # probes drive recovery
                exclude = frozenset(tried)
                be = None
                if prefer is not None and prefer not in exclude:
                    cand = self.backends.get(prefer)
                    if cand is not None and cand.state == ACTIVE \
                            and cand.breaker.state != _rp.OPEN \
                            and cand.breaker.allow():
                        be = cand
                if be is None:
                    be = self.backends.pick(session=session,
                                            exclude=exclude,
                                            prefix_hashes=prefix_hashes)
                if be is None and tried:
                    tried.clear()
                    be = self.backends.pick(session=session,
                                            prefix_hashes=prefix_hashes)
                if be is None:
                    last = RouterError(
                        f"{self.name}: no routable backend "
                        f"({len(self.backends)} configured)")
                    self._retry.sleep(attempt)
                    attempt += 1
                    continue
                if deadline is not None:
                    # recomputed per attempt: a retry must not
                    # resurrect budget the earlier attempt spent
                    meta = dict(meta)
                    meta[_rp.WIRE_KEY] = deadline.to_wire()
                if used_backend:
                    # this buffer already hit a wire and lost it:
                    # landing on `be` now is a failover re-dispatch
                    _FAILOVER_TOTAL.labels(self.name).inc()
                    _events.record(
                        "router.failover",
                        f"{self.name}: re-dispatching to "
                        f"{be.endpoint} after backend failure",
                        severity="warning", element=self.name,
                        backend=be.endpoint)
                try:
                    t0 = time.monotonic()
                    rmeta, rpayload = self._attempt(
                        be, meta, payload, deadline, session, tried)
                    rtt = time.monotonic() - t0
                    self._observe_latency(rtt)
                    _RTT.labels(self.name).observe(rtt)
                    rhook = _slo.ROUTER_SLO_HOOK
                    if rhook is not None:
                        rhook.record_dispatch(
                            session, len(payload), len(rpayload))
                    span.set_attribute("backend", be.endpoint)
                    if session is not None:
                        self.backends.note_session(session, be.endpoint)
                    self.backends.reap_drained()
                    return rmeta, rpayload
                except (ConnectionError, OSError,
                        QueryProtocolError) as e:
                    last = e
                    used_backend = True
                    tried.add(be.endpoint)
                    log.warning("router %s: backend %s failed "
                                "(attempt %d/%d): %s", self.name,
                                be.endpoint, budget.used,
                                budget.attempts, e)
                    if not budget.exhausted:
                        self._retry.sleep(attempt)
                attempt += 1
            span.set_attribute("error", True)
            raise RouterError(
                f"{self.name}: request failed on every routable "
                f"backend after {budget.used} attempt(s): {last}")
        finally:
            span.end()

    def _attempt(self, be: Backend, meta: Dict[str, Any], payload: bytes,
                 deadline: Optional[_rp.Deadline],
                 session: Optional[str], tried: set
                 ) -> Tuple[Dict[str, Any], bytes]:
        """One placement: the primary round trip, hedged with a second
        backend when armed and the P95 window elapses first."""
        caps = self._caps()
        _DISPATCH_TOTAL.labels(self.name, be.endpoint).inc()
        if self.hedge_ms <= 0:
            # no manual floor: the autotuner arms hedging from the
            # observed P95 alone once the latency window holds enough
            # samples to make that quantile real (hedge_delay_s's own
            # threshold) — `--hedge-ms` stops being required knowledge
            tn = _tune.TUNE_HOOK
            if tn is None or not tn.auto_hedge:
                return be.request(meta, payload, caps)
            with self._lat_lock:
                n = len(self._latencies)
            if n < 20:
                return be.request(meta, payload, caps)
        return self._hedged(be, meta, payload, caps, session, tried)

    def _hedged(self, primary: Backend, meta: Dict[str, Any],
                payload: bytes, caps: str, session: Optional[str],
                tried: set) -> Tuple[Dict[str, Any], bytes]:
        """First-response-wins across the primary and (after the hedge
        delay) one peer. Both run full round trips — the loser's result
        is discarded, not aborted, so its connection stays in protocol
        sync for the next request."""
        done = threading.Condition()
        results: List[Tuple[str, Any, Any]] = []  # (who, result|None, err)

        def run(be: Backend, who: str) -> None:
            try:
                r = be.request(meta, payload, caps)
                err = None
            except (ConnectionError, OSError, QueryProtocolError) as e:
                r, err = None, e
            with done:
                results.append((who, r, err))
                done.notify_all()

        t_p = threading.Thread(target=run, args=(primary, "primary"),
                               daemon=True,
                               name=f"router-primary:{self.name}")
        t_p.start()
        delay = self.hedge_delay_s()
        with done:
            done.wait_for(lambda: results, timeout=delay)
        hedge_be: Optional[Backend] = None
        if not results:
            # primary is past the P95 window: hedge onto a DIFFERENT
            # backend (exclude the primary and this buffer's failures)
            hedge_be = self.backends.pick(
                exclude=frozenset(tried) | {primary.endpoint})
            if hedge_be is not None:
                _rp.record_hedge(
                    self.name,
                    f"{self.name}: hedging {primary.endpoint} -> "
                    f"{hedge_be.endpoint} after {delay * 1e3:.0f}ms",
                    backend=hedge_be.endpoint)
                _DISPATCH_TOTAL.labels(
                    self.name, hedge_be.endpoint).inc()
                threading.Thread(
                    target=run, args=(hedge_be, "hedge"), daemon=True,
                    name=f"router-hedge:{self.name}").start()
        expected = 2 if hedge_be is not None else 1
        with done:
            while True:
                for who, r, err in results:
                    if r is not None:
                        return r
                if len(results) >= expected:
                    # every runner failed: surface the primary's error
                    for who, r, err in results:
                        if who == "primary":
                            raise err
                    raise results[0][2]
                done.wait(0.05)

    def snapshot(self) -> Dict[str, Any]:
        """Programmatic view for tests/debugging."""
        out = []
        for be in self.backends.backends():
            out.append({
                "endpoint": be.endpoint, "state": be.state,
                "instance": be.instance, "inflight": be.inflight,
                "ewma_s": be.ewma_s, "breaker": be.breaker.state,
                "dispatched": be.dispatched,
            })
        return {"name": self.name, "hedge_ms": self.hedge_ms,
                "backends": out}

    def close(self) -> None:
        self.backends.close()


#: live router registry (WeakSet, like obs/tracing's pipeline
#: registry): a collected router never lingers in a debug bundle's
#: routing view
_live_routers: "weakref.WeakSet" = weakref.WeakSet()


def routing_view() -> List[Dict[str, Any]]:
    """Snapshot of every live router — the bundle capture's routing
    evidence (who was routable, breakers, inflight, EWMA) at incident
    time."""
    return [r.snapshot() for r in list(_live_routers)]


class _ShedSignal(Exception):
    """Internal: dispatch hit an expired deadline — the client sheds
    the buffer (legal drop) instead of erroring or falling back."""
