"""Distributed query/offload layer: wire protocol, client/server elements,
hybrid broker discovery."""

from .protocol import Cmd, pack_message, recv_message, send_message
from .hybrid import DiscoveryBroker, discover, register_node, unregister_node

__all__ = ["Cmd", "pack_message", "recv_message", "send_message",
           "DiscoveryBroker", "discover", "register_node", "unregister_node"]
