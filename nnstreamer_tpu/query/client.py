"""tensor_query_client — per-buffer remote offload element.

Reference: gst/nnstreamer/tensor_query/tensor_query_client.c (chain :658:
send frame, receive result, push downstream; retry/reconnect :769-776;
broker-based discovery via tensor_query_hybrid when ``operation`` is set).

Props: host/port (direct), or ``operation=<topic>`` + broker-host/port for
hybrid discovery; ``sparse=true`` compresses request payloads;
``max-request-retry`` is ONE shared retry budget per request (connect
dials + resends draw from the same pool, with full-jitter exponential
backoff between attempts — resilience/policy.py). A circuit breaker
tracks the remote path; with ``fallback=`` set (``passthrough`` or a
local element kind) an open breaker routes buffers to the local path
and health reports DEGRADED instead of erroring the pipeline.
``deadline-ms`` stamps a per-buffer deadline that is shed client-side
when expired and travels on the wire as remaining budget;
``drain-timeout-s`` bounds the EOS drain of pipelined results.

``async_depth=N`` (TPU-first addition, default 1 = reference-equivalent
synchronous semantics): keep up to N requests in flight on the one TCP
stream. A server whose filter runs on a high-RTT device (a tunneled TPU)
costs one device round trip per frame; with N>1 those round trips overlap
and offload throughput approaches N/RTT instead of 1/RTT — the query-layer
analog of tensor_decoder's ``async_depth``. Results return in order (the
stream and the server pipeline are serial), so PTS restoration is a FIFO.
Retry/reconnect applies to the synchronous path; in pipelined mode a
connection failure fails the in-flight window (pipeline error) rather than
silently replaying frames.
"""

from __future__ import annotations

import collections
import socket
import threading
import time
import weakref
from typing import Any, Optional

from ..core.buffer import Buffer
from ..core.log import logger
from ..core.types import Caps, TensorFormat
from ..graph.element import (
    Element,
    FlowReturn,
    Pad,
    join_or_warn,
    make_element,
    register_element,
)
from ..obs import events as _events
from ..obs import fleet as _fleet
from ..obs import health as _health
from ..obs import metrics as _obs
from ..obs import tracing as _tracing
from ..resilience import policy as _rp
from .protocol import (
    Cmd,
    QueryProtocolError,
    buffer_to_payload,
    pack_message,
    payload_to_buffer,
    recv_message,
    send_message,
)

log = logger("query")


class _FallbackTap(Element):
    """Internal sink for a client's fallback element: whatever the
    fallback produces is forwarded out of the hosting client's src pad,
    so downstream sees one stream whether frames went remote or local.
    Built only by TensorQueryClient — never registered."""

    ELEMENT_NAME = "fallback_tap"

    def __init__(self, owner: "TensorQueryClient"):
        super().__init__(name=f"{owner.name}.fallback_tap")
        self.add_sink_pad(template=Caps.any_tensors())
        self._owner = owner

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        return self._owner.push(buf)


@register_element
class TensorQueryClient(Element):
    ELEMENT_NAME = "tensor_query_client"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 5001
        self.operation: Optional[str] = None  # hybrid topic
        self.broker_host = "127.0.0.1"
        self.broker_port = 5300
        self.sparse = False
        self.max_request_retry = 3
        self.timeout_s = 10.0
        self.async_depth = 1  # >1: pipelined requests (see module doc)
        # resilience knobs (resilience/policy.py). max_request_retry is
        # a single SHARED RetryBudget per request — connect dials and
        # request resends draw from one pool instead of multiplying.
        self.retry_base_s = 0.05    # backoff: first-retry jitter cap
        self.retry_max_s = 1.0      # backoff: ceiling for later retries
        self.breaker_threshold = 5  # consecutive failures to open
        self.breaker_reset_s = 5.0  # open→half-open cooldown
        #: local degradation when the remote path is down: "passthrough"
        #: forwards input buffers unchanged; any registered element kind
        #: (e.g. a local tensor_filter) processes them instead. Unset →
        #: failures keep today's error semantics.
        self.fallback: Any = None
        #: stamp this per-buffer deadline budget (ms) on ingress when
        #: upstream didn't already attach one; 0 = no deadline
        self.deadline_ms = 0.0
        #: EOS drain patience for pipelined in-flight results
        #: (was a hardcoded 60 s)
        self.drain_timeout_s = 60.0
        #: routed mode: a comma-separated "host:port,host:port" string
        #: (or list) of tensor_query servers. Set, it replaces the
        #: single host/port link with a QueryRouter — per-backend
        #: breakers, two-choice placement, mid-stream failover. Unset
        #: (default), no router object exists and chain() pays one
        #: is-None check — the chaos-hook zero-overhead contract.
        self.backends: Any = None
        #: hedged dispatch delay floor in ms (routed mode only; 0 =
        #: hedging off). The live delay is max(observed P95, hedge_ms).
        self.hedge_ms = 0.0
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self.add_src_pad(template=Caps.any_tensors())
        self._sock: Optional[socket.socket] = None
        self._caps_out_sent = False
        self._pending: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._reader: Optional[threading.Thread] = None
        self._reader_error: Optional[Exception] = None
        self._pong = False
        # _pending entries are mutable [pts, duration, offset, sent]
        # records; `sent` flips True under _cv once send_message returns.
        # The reader's error path counts only sent entries as lost; a
        # frame whose send raced the connection death is caught by its
        # own chain call via _reader_dead (see _reader_loop / the
        # post-send check in _chain_pipelined) — no silent-loss window.
        self._reader_dead = False
        self._last_activity = 0.0
        #: reused connections idle longer than this get a PING/PONG probe
        #: before the next frame (a peer that died while idle is only
        #: detectable by traffic); short gaps skip the probe so steady
        #: streams never pay the extra round trip
        self.idle_probe_s = 0.5
        # breaker guarding the remote path; it only GATES sends when a
        # fallback is configured (without one, refusing to try would
        # just turn retry errors into faster errors) but it always
        # tracks state for the gauge/events
        self._breaker = _rp.CircuitBreaker(
            f"query:{self.name}",
            failure_threshold=int(self.breaker_threshold),
            reset_s=float(self.breaker_reset_s))
        self._fallback_el: Optional[Element] = None
        self._fallback_tap: Optional[_FallbackTap] = None
        self._fb_active = False      # fallback carried the last buffer
        self._last_deadline: Optional[_rp.Deadline] = None
        #: multi-backend router (query/router.py); stays None without
        #: ``backends=`` so the routed branch in chain() costs one
        #: attribute load + is-None check
        self._router = None
        #: EOS drain in progress: _connect refuses to dial (the drain
        #: is waiting for RESULTs already owed on the existing link —
        #: a fresh connection can't deliver them, only leak)
        self._draining = False
        # offload telemetry (obs subsystem; message/byte counts live at
        # the protocol layer): dials, request round trips, and the
        # pipelined in-flight window (collection-time read, no hot cost)
        reg = _obs.registry()
        self._m_reconnects = reg.counter(
            "nnstpu_query_reconnects_total",
            "Client connection dials (first connect + reconnects)",
            ("element",)).labels(self.name)
        self._m_rtt = reg.histogram(
            "nnstpu_query_roundtrip_seconds",
            "Request submit to result round-trip latency",
            ("element",)).labels(self.name)
        reg.gauge(
            "nnstpu_query_inflight_depth",
            "Pipelined requests currently in flight",
            ("element",)).labels(self.name).set_function(
                lambda: len(self._pending))
        # health (obs/health.py): connection-liveness component (the
        # watchdog's reconnect-storm rule reads its "reconnect" count)
        # and the "query connected" readiness condition — the shared
        # no-op component / a skipped registration while health is off.
        # Weakref probes: the registry never pins a retired element.
        ref = weakref.ref(self)
        self._hc = _health.component(
            f"query.client:{self.name}", kind="query",
            probe=lambda: (lambda c: None if c is None else
                           {"connected": c._sock is not None,
                            "in_flight": len(c._pending),
                            "routed": c._router is not None})(ref()),
            attrs={"element": self.name})
        # routed mode has no single _sock; ready = any active backend
        _health.add_readiness(
            f"query:{self.name}",
            lambda: (lambda c: None if c is None
                     else (any(b.state == "active"
                               for b in c._router.backends.backends())
                           if c._router is not None
                           else c._sock is not None))(ref()))

    # -- connection ---------------------------------------------------------- #
    def _resolve_endpoints(self) -> list:
        if self.operation:
            from .hybrid import discover

            nodes = discover(self.operation, self.broker_host,
                             int(self.broker_port))
            if not nodes:
                raise ConnectionError(
                    f"hybrid discovery: no servers for {self.operation!r}")
            return nodes  # failover across all advertised nodes
        return [(self.host, int(self.port))]

    def _connect(self) -> socket.socket:
        if self._draining:
            # EOS drain must never dial: a new connection can't carry
            # the in-flight results the drain is waiting for, and the
            # old drain/reconnect race left sockets behind
            raise ConnectionError(
                f"{self.name}: draining — refusing to open a connection")
        last: Optional[Exception] = None
        for host, port in self._resolve_endpoints():
            sock: Optional[socket.socket] = None
            # any failure on this node — TCP connect, a reset mid-handshake,
            # a protocol violation, or a deny — moves on to the next node
            try:
                sock = socket.create_connection((host, port),
                                                timeout=self.timeout_s)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                send_message(sock, Cmd.INFO_REQ,
                             {"caps": str(self.sink_pad.caps or "")})
                cmd, meta, _ = recv_message(sock)
                if cmd is Cmd.INFO_DENY:
                    raise ConnectionError(
                        f"server denied connection: "
                        f"{meta.get('error', meta)}")
                if cmd is not Cmd.INFO_APPROVE:
                    raise ConnectionError(f"unexpected handshake reply "
                                          f"{cmd}: {meta}")
                self._m_reconnects.inc()
                self._hc.count("reconnect")  # watchdog storm-rule input
                self._hc.beat()
                self._hc.set_status(_health.Status.OK,
                                    f"connected to {host}:{port}")
                _events.record("query.connect",
                               f"{self.name}: connected to {host}:{port}",
                               element=self.name)
                return sock
            except (OSError, QueryProtocolError, ConnectionError) as e:
                last = e
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
        raise ConnectionError(f"no reachable server: {last}")

    def _ensure_conn(self) -> socket.socket:
        """Dial once if unconnected. Retry ownership lives with the
        caller's RetryBudget: the nested per-call retry loop that used
        to run here multiplied with chain()'s into retry² dials per
        frame — now both draw from one budget in _chain_sync."""
        if self._sock is None:
            self._sock = self._connect()
        return self._sock

    def _retry_policy(self) -> "_rp.RetryPolicy":
        """Backoff from the current props (full jitter — reconnecting
        clients decorrelate instead of re-arriving in waves)."""
        return _rp.RetryPolicy(base_s=float(self.retry_base_s),
                               max_s=float(self.retry_max_s))

    def start(self) -> None:
        self._caps_out_sent = False
        self._reader_error = None
        self._draining = False
        if self.fallback and self._fallback_el is None \
                and self.fallback != "passthrough":
            self._build_fallback()
        if self.backends and self._router is None:
            self._build_router()

    def _build_router(self) -> None:
        from . import router as _router_mod

        eps = _router_mod.parse_endpoints(self.backends)
        bset = _router_mod.BackendSet(
            eps, owner=self.name, timeout_s=float(self.timeout_s),
            breaker_threshold=int(self.breaker_threshold),
            breaker_reset_s=float(self.breaker_reset_s))
        self._router = _router_mod.QueryRouter(
            bset, name=self.name,
            max_request_retry=int(self.max_request_retry),
            hedge_ms=float(self.hedge_ms or 0.0),
            retry_policy=self._retry_policy())
        ref = weakref.ref(self)
        self._router.set_caps_provider(
            lambda: (lambda c: str(c.sink_pad.caps or "")
                     if c is not None else "")(ref()))

    @property
    def router(self):
        """The live QueryRouter in routed mode (None otherwise) — the
        handle for live backend add/remove/drain."""
        return self._router

    def _build_fallback(self) -> None:
        """Materialize the ``fallback=`` property: a callable becomes a
        local tensor_filter wrapping it, a string names a registered
        element kind. Its output feeds a tap that forwards out of this
        client's src pad."""
        fb = self.fallback
        if callable(fb):
            el = make_element("tensor_filter", f"{self.name}.fallback",
                              model=fb)
        else:
            el = make_element(str(fb).strip(), f"{self.name}.fallback")
        if not el.sink_pads or not el.src_pads:
            raise ValueError(
                f"fallback element {fb!r} must have sink and src pads")
        tap = _FallbackTap(self)
        el.src_pads[0].link(tap.sink_pads[0])
        el.bus = tap.bus = self.bus
        el.start()
        self._fallback_el, self._fallback_tap = el, tap
        caps = self.sink_pad.caps
        if caps is not None:
            el.on_caps(el.sink_pads[0], caps)

    def stop(self) -> None:
        if self._router is not None:
            self._router.close()
            self._router = None
        if self._sock is not None:
            try:
                # shutdown (not just close) unblocks a reader thread
                # parked in recv; bare close can leave it hanging
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        r = self._reader
        if r is not None and r is not threading.current_thread():
            join_or_warn(r, self.name)
        self._reader = None
        with self._cv:
            self._pending.clear()
            self._cv.notify_all()

    # -- negotiation --------------------------------------------------------- #
    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        if self._fallback_el is not None:
            # the local fallback negotiates the same input the remote
            # path would have seen
            self._fallback_el.on_caps(self._fallback_el.sink_pads[0], caps)
        # result stream is shape-dynamic from the client's viewpoint: declare
        # flexible; static caps could be fetched from the server in future
        self.send_caps_all(Caps.tensors(format=TensorFormat.FLEXIBLE))

    # -- pipelined dataflow --------------------------------------------------- #
    def _reader_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                cmd, rmeta, rpayload = recv_message(sock)
                if cmd is Cmd.PONG:
                    with self._cv:
                        self._pong = True
                        self._cv.notify_all()
                    continue
                if cmd is Cmd.ERROR:
                    raise QueryProtocolError(rmeta.get("error", "server error"))
                if cmd is not Cmd.RESULT:
                    raise QueryProtocolError(f"unexpected reply {cmd}")
                with self._cv:
                    if not self._pending:
                        raise QueryProtocolError("unsolicited RESULT")
                    pts, duration, offset = self._pending[0][:3]
                    span, root = self._pending[0][5], self._pending[0][6]
                out = payload_to_buffer(rmeta, rpayload)
                out.pts, out.duration, out.offset = pts, duration, offset
                if span.recording:
                    # downstream elements keep tracing inside this
                    # request's trace (the result is its continuation)
                    out.meta[_tracing.CTX_META_KEY] = span.context
                    if root is not None:
                        out.meta[_tracing.ROOT_META_KEY] = root
                self.push(out)
                with self._cv:
                    # pop only AFTER the push: an EOS drain waiting on the
                    # window must not race past a result still mid-push
                    done = self._pending.popleft()
                    self._cv.notify_all()
                done[5].end()
                self._m_rtt.observe(time.monotonic() - done[4])
        except (ConnectionError, OSError, QueryProtocolError) as e:
            with self._cv:
                # SENT frames (send_message returned) are lost; entries
                # still mid-send are NOT counted — their chain call owns
                # them: either its send raises (it pops and retries) or
                # its send "succeeded" into a dead connection, which it
                # detects via _reader_dead after flipping the sent flag
                # (closing the silent-loss window either way)
                self._reader_dead = True
                lost = sum(1 for entry in self._pending if entry[3])
                if lost > 0 or not isinstance(e, OSError):
                    self._reader_error = e
                    self.post_error(f"query reader failed with "
                                    f"{lost} in flight: {e}", exc=e)
                    self._pending.clear()
                self._cv.notify_all()

    def _remove_entry(self, entry) -> None:
        """Remove a pending record by IDENTITY (value equality would
        delete a different in-flight frame with equal pts/dur/offset —
        e.g. two untimestamped frames); no-op if the reader's error path
        already cleared the deque."""
        for i, e in enumerate(self._pending):
            if e is entry:
                del self._pending[i]
                return

    def _reset_conn(self) -> None:
        """Drop the connection + reader so the next attempt dials fresh.
        Only safe with nothing in flight. stop() joins the old reader
        BEFORE the state reset — an unjoined reader could wake later and
        misread the new connection's pending window."""
        _events.record("query.reconnect",
                       f"{self.name}: dropping connection for redial",
                       element=self.name)
        self.stop()
        self._reader_error = None

    def _probe_idle_conn(self, sock: socket.socket) -> bool:
        """PING/PONG a reused idle connection. A peer that died while we
        were idle is only detectable by traffic — without this, the first
        frame after an idle gap would be entrusted to a dead socket and
        lost to an async RST."""
        with self._cv:
            self._pong = False
        try:
            send_message(sock, Cmd.PING, {})
        except OSError:
            return False
        deadline = time.monotonic() + min(self.timeout_s, 5.0)
        with self._cv:
            while not self._pong and self._reader_error is None \
                    and self._reader is not None \
                    and self._reader.is_alive() \
                    and time.monotonic() < deadline:
                self._cv.wait(0.1)
            return self._pong

    def _maybe_push_obs(self, sock: socket.socket) -> None:
        """Piggyback one fleet ``OBS_PUSH`` frame ahead of a DATA send
        when the push interval has elapsed (obs/fleet.py). Fleet off →
        one module-global None check, zero wire bytes. Sent raw (no
        tracing wrap, no reply expected) on the caller's socket and
        thread, so it can never interleave with a request frame."""
        frame = _fleet.wire_frame_due()
        if frame is not None:
            pmeta, ppayload = frame
            sock.sendall(pack_message(Cmd.OBS_PUSH, pmeta, ppayload))

    def _chain_pipelined(self, buf: Buffer, depth: int) -> FlowReturn:
        meta, payload = buffer_to_payload(buf, sparse=bool(self.sparse))
        dl = _rp.deadline_of(buf)
        retry = self._retry_policy()
        # per-request span: submit → result popped by the reader (ended
        # there); NOOP when tracing is off, so every span touch below
        # is a no-op method on a shared singleton
        rspan = _tracing.start_span(
            "query.request",
            parent=buf.meta.get(_tracing.CTX_META_KEY),
            attrs={"element": self.name, "pipelined": True})
        for attempt in range(max(int(self.max_request_retry), 1)):
            if dl is not None and dl.expired():
                rspan.end()
                return self._shed(buf, f"deadline expired after "
                                       f"{attempt} attempt(s)")
            with self._cv:
                if self._reader_error is not None:
                    return FlowReturn.ERROR  # in-flight loss, on the bus
                idle = not self._pending
                reader_dead = self._reader is not None \
                    and not self._reader.is_alive()
            if reader_dead:
                if not idle:
                    self.post_error("query reader died with frames queued")
                    return FlowReturn.ERROR
                self._reset_conn()  # clean close between streams: redial
            if self._sock is None:
                try:
                    # single dial per outer attempt (same no-multiply
                    # rule the sync path now gets from its RetryBudget)
                    self._sock = self._connect()
                    self._breaker.record_success()
                except (ConnectionError, OSError):
                    self._breaker.record_failure()
                    retry.sleep(attempt)
                    continue
            sock = self._sock
            fresh = self._reader is None
            if fresh:
                self._reader_dead = False
                # the reader blocks in recv indefinitely (stop() unblocks
                # it via shutdown); the connect timeout must NOT ride
                # along or a >timeout_s gap between results (e.g. a
                # server-side XLA compile) would kill the stream
                sock.settimeout(None)
                self._reader = threading.Thread(
                    target=self._reader_loop, args=(sock,), daemon=True,
                    name=f"qclient-reader:{self.name}")
                self._reader.start()
            stale = (idle and not fresh and
                     time.monotonic() - self._last_activity
                     > float(self.idle_probe_s))
            if stale and not self._probe_idle_conn(sock):
                self._reset_conn()
                continue  # dead idle connection: retry on a fresh one
            with self._cv:
                while len(self._pending) >= depth \
                        and self._reader_error is None:
                    self._cv.wait(0.1)
                if self._reader_error is not None:
                    return FlowReturn.ERROR
                # 5th field: submit stamp for the round-trip histogram;
                # 6th/7th: the request span the reader thread will close
                # and the trace root it re-stamps onto the result buffer
                entry = [buf.pts, buf.duration, buf.offset, False,
                         time.monotonic(), rspan,
                         buf.meta.get(_tracing.ROOT_META_KEY)]
                self._pending.append(entry)
            try:
                self._maybe_push_obs(sock)
                if dl is not None:
                    # wire form is REMAINING ms, re-anchored on the
                    # server's own clock — recomputed per attempt so
                    # retries don't resurrect spent budget
                    meta[_rp.WIRE_KEY] = dl.to_wire()
                if rspan.recording:
                    # current-context window around the send so the wire
                    # meta carries this request's context to the server
                    tok = _tracing._set_current(rspan.context)
                    try:
                        send_message(sock, Cmd.DATA, meta, payload)
                    finally:
                        _tracing._reset_current(tok)
                else:
                    send_message(sock, Cmd.DATA, meta, payload)
                with self._cv:
                    entry[3] = True  # on the wire: reader owns its fate
                    if self._reader_error is not None or self._reader_dead:
                        # the connection died around this send and the
                        # reader could not have counted this entry (it
                        # was unsent when the reader examined pending):
                        # report the possible loss here instead of
                        # silently returning OK
                        if self._reader_error is None:
                            self.post_error(
                                "query connection lost with a frame "
                                "just handed to the transport")
                        self._remove_entry(entry)
                        return FlowReturn.ERROR
                self._last_activity = time.monotonic()
                return FlowReturn.OK
            except OSError:
                with self._cv:
                    self._remove_entry(entry)  # never went out
                    others = bool(self._pending)
                if others or self._reader_error is not None:
                    # sent frames are (or already were) reported lost
                    if self._reader_error is None:
                        self.post_error(
                            "query send failed with frames in flight")
                    return FlowReturn.ERROR
                self._reset_conn()  # nothing else at risk: retry fresh
        rspan.end()
        if self.fallback:
            return self._route_fallback(buf, "request failed after retries")
        self._hc.set_status(_health.Status.FAILED,
                            "request failed after retries")
        self.post_error("query: request failed after retries")
        return FlowReturn.ERROR

    def _drain_pending(self, timeout: Optional[float] = None) -> None:
        if timeout is None:
            timeout = float(self.drain_timeout_s)
        dl = self._last_deadline
        if dl is not None:
            # results for past-deadline requests are worthless; don't
            # out-wait the work's own budget
            timeout = min(timeout, max(dl.remaining_s(), 0.0))
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending and self._reader_error is None \
                    and time.monotonic() < deadline:
                self._cv.wait(0.2)
            abandoned = len(self._pending)
        if abandoned and self._reader_error is None:
            log.warning("%s: EOS drain gave up with %d result(s) still "
                        "pending after %.1fs", self.name, abandoned, timeout)
            _events.record("query.drain_abandoned",
                           f"{self.name}: EOS drain gave up with "
                           f"{abandoned} result(s) pending",
                           severity="warning", element=self.name,
                           pending=abandoned)

    def on_eos(self) -> None:
        # all in-flight results must be pushed before EOS propagates.
        # The drain window is strictly read-only on connection state:
        # no dialing (see _connect) and, in routed mode, no membership
        # growth — a backend added mid-drain could never owe results.
        self._draining = True
        if self._router is not None:
            self._router.draining = True
        try:
            self._drain_pending()
        finally:
            self._draining = False

    # -- degraded paths -------------------------------------------------------- #
    def _shed(self, buf: Buffer, why: str) -> FlowReturn:
        """Drop a past-deadline buffer (the graph's legal drop: return
        OK without pushing) — sending it would spend wire and server
        time on a result nobody can use."""
        self._hc.count("shed")
        _rp.record_shed("query", f"{self.name}: shed buffer ({why})",
                        element=self.name)
        return FlowReturn.OK

    def _route_fallback(self, buf: Buffer, why: str) -> FlowReturn:
        """Degraded mode: hand the buffer to the local fallback element
        (or pass it through) instead of the dead remote path. Health
        goes DEGRADED — visibly impaired, not failed: /healthz stays
        200 and the pipeline keeps flowing."""
        self._fb_active = True
        self._hc.set_status(_health.Status.DEGRADED,
                            f"fallback active: {why}")
        _rp.record_fallback(self.name, f"{self.name}: {why} — buffer "
                                       f"routed to local fallback",
                            reason=why)
        el = self._fallback_el
        if el is None:  # passthrough
            return self.push(buf)
        ret = el._chain_entry(el.sink_pads[0], buf)
        return ret if ret is not None else FlowReturn.OK

    def _remote_restored(self) -> None:
        """A remote round trip succeeded after fallback traffic: the
        breaker probe closed the circuit, so un-degrade."""
        self._fb_active = False
        self._hc.set_status(_health.Status.OK, "remote path restored")
        _events.record("query.remote_restored",
                       f"{self.name}: remote path restored after fallback",
                       element=self.name)

    # -- dataflow ------------------------------------------------------------- #
    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        # deadline: adopt upstream's, or stamp this element's budget
        dl = _rp.deadline_of(buf)
        if dl is None and float(self.deadline_ms or 0) > 0:
            dl = _rp.Deadline.after_ms(float(self.deadline_ms))
            _rp.set_deadline(buf, dl)
        if dl is not None:
            self._last_deadline = dl
            if dl.expired():
                return self._shed(buf, "deadline expired before send")
        # routed mode: per-backend breakers + placement live in the
        # router; disabled cost is this one is-None check
        if self._router is not None:
            return self._chain_routed(buf, dl)
        # breaker gate — only with a fallback to route to (without one,
        # refusing to try would just fail faster than trying)
        if self.fallback and not self._breaker.allow():
            return self._route_fallback(buf, "breaker open")
        depth = int(self.async_depth or 1)
        if depth > 1:
            return self._chain_pipelined(buf, depth)
        return self._chain_sync(buf, dl)

    def _chain_routed(self, buf: Buffer,
                      dl: Optional["_rp.Deadline"]) -> Optional[FlowReturn]:
        from .router import RouterError, _ShedSignal

        meta, payload = buffer_to_payload(buf, sparse=bool(self.sparse))
        sess = buf.meta.get("session")
        if sess is not None:
            # affinity key rides the wire so the serving side can pin
            # KV/prefix reuse; the router hashes it for placement
            meta["session"] = str(sess)
        try:
            rmeta, rpayload = self._router.dispatch(
                meta, payload, deadline=dl,
                session=str(sess) if sess is not None else None)
        except _ShedSignal:
            return self._shed(buf, "deadline expired in router")
        except RouterError as e:
            if self.fallback:
                return self._route_fallback(buf, f"all backends down: {e}")
            self._hc.set_status(_health.Status.FAILED,
                                f"all backends down: {e}")
            _events.record("query.connect_failed",
                           f"{self.name}: all backends down: {e}",
                           severity="error", element=self.name)
            raise ConnectionError(
                "tensor_query_client: request failed on every backend")
        self._hc.beat()
        if self._fb_active:
            self._remote_restored()
        out = payload_to_buffer(rmeta, rpayload)
        out.pts, out.duration, out.offset = buf.pts, buf.duration, buf.offset
        ctx = buf.meta.get(_tracing.CTX_META_KEY)
        if ctx is not None:
            out.meta[_tracing.CTX_META_KEY] = ctx
            root = buf.meta.get(_tracing.ROOT_META_KEY)
            if root is not None:
                out.meta[_tracing.ROOT_META_KEY] = root
        return self.push(out)

    def _chain_sync(self, buf: Buffer,
                    dl: Optional["_rp.Deadline"]) -> Optional[FlowReturn]:
        meta, payload = buffer_to_payload(buf, sparse=bool(self.sparse))
        # ONE retry budget for the whole request: connect dials and
        # request resends draw from the same max_request_retry pool
        # (previously chain x _ensure_conn multiplied into retry² dials)
        budget = _rp.RetryBudget(self.max_request_retry, site="query")
        retry = self._retry_policy()
        last: Optional[Exception] = None
        # one span per offload round trip: covers the wire send, the
        # server-side remote-parented spans, and the result receive —
        # NOOP (flag check only) when tracing is off
        with _tracing.start_span(
                "query.request",
                parent=buf.meta.get(_tracing.CTX_META_KEY),
                attrs={"element": self.name}) as rspan:
            while budget.take():
                if dl is not None and dl.expired():
                    return self._shed(
                        buf, f"deadline expired after {budget.used - 1} "
                             f"attempt(s)")
                try:
                    sock = self._ensure_conn()
                    self._maybe_push_obs(sock)
                    if dl is not None:
                        # wire form is REMAINING ms (re-anchored on the
                        # server's clock); recomputed per attempt so a
                        # retry doesn't resurrect spent budget
                        meta[_rp.WIRE_KEY] = dl.to_wire()
                    t_send = time.monotonic()
                    send_message(sock, Cmd.DATA, meta, payload)
                    cmd, rmeta, rpayload = recv_message(sock)
                    if cmd is Cmd.ERROR:
                        raise QueryProtocolError(
                            rmeta.get("error", "server error"))
                    if cmd is not Cmd.RESULT:
                        raise QueryProtocolError(f"unexpected reply {cmd}")
                    self._m_rtt.observe(time.monotonic() - t_send)
                    self._breaker.record_success()
                    if self._fb_active:
                        self._remote_restored()
                    out = payload_to_buffer(rmeta, rpayload)
                    out.pts, out.duration, out.offset = \
                        buf.pts, buf.duration, buf.offset
                    if rspan.recording:
                        out.meta[_tracing.CTX_META_KEY] = rspan.context
                        root = buf.meta.get(_tracing.ROOT_META_KEY)
                        if root is not None:
                            # the result buffer continues the request's
                            # trace; the sink must still close its root
                            out.meta[_tracing.ROOT_META_KEY] = root
                    return self.push(out)
                except (ConnectionError, OSError, QueryProtocolError) as e:
                    last = e
                    self._breaker.record_failure()
                    log.warning("query attempt %d/%d failed: %s",
                                budget.used, budget.attempts, e)
                    self.stop()  # drop connection, retry fresh
                    if not budget.exhausted:
                        retry.sleep(budget.used - 1)
        if self.fallback:
            return self._route_fallback(
                buf, f"request failed after retries: {last}")
        self._hc.set_status(_health.Status.FAILED,
                            f"request failed after retries: {last}")
        _events.record("query.connect_failed",
                       f"{self.name}: request failed after retries: {last}",
                       severity="error", element=self.name)
        raise ConnectionError("tensor_query_client: request failed after retries")
