"""tensor_query_client — per-buffer remote offload element.

Reference: gst/nnstreamer/tensor_query/tensor_query_client.c (chain :658:
send frame, receive result, push downstream; retry/reconnect :769-776;
broker-based discovery via tensor_query_hybrid when ``operation`` is set).

Props: host/port (direct), or ``operation=<topic>`` + broker-host/port for
hybrid discovery; ``sparse=true`` compresses request payloads;
``max-request-retry`` bounds reconnect attempts.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Optional

from ..core.buffer import Buffer
from ..core.log import logger
from ..core.types import Caps, TensorFormat
from ..graph.element import Element, FlowReturn, Pad, register_element
from .protocol import (
    Cmd,
    QueryProtocolError,
    buffer_to_payload,
    payload_to_buffer,
    recv_message,
    send_message,
)

log = logger("query")


@register_element
class TensorQueryClient(Element):
    ELEMENT_NAME = "tensor_query_client"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "127.0.0.1"
        self.port = 5001
        self.operation: Optional[str] = None  # hybrid topic
        self.broker_host = "127.0.0.1"
        self.broker_port = 5300
        self.sparse = False
        self.max_request_retry = 3
        self.timeout_s = 10.0
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self.add_src_pad(template=Caps.any_tensors())
        self._sock: Optional[socket.socket] = None
        self._caps_out_sent = False

    # -- connection ---------------------------------------------------------- #
    def _resolve_endpoints(self) -> list:
        if self.operation:
            from .hybrid import discover

            nodes = discover(self.operation, self.broker_host,
                             int(self.broker_port))
            if not nodes:
                raise ConnectionError(
                    f"hybrid discovery: no servers for {self.operation!r}")
            return nodes  # failover across all advertised nodes
        return [(self.host, int(self.port))]

    def _connect(self) -> socket.socket:
        last: Optional[Exception] = None
        for host, port in self._resolve_endpoints():
            sock: Optional[socket.socket] = None
            # any failure on this node — TCP connect, a reset mid-handshake,
            # a protocol violation, or a deny — moves on to the next node
            try:
                sock = socket.create_connection((host, port),
                                                timeout=self.timeout_s)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                send_message(sock, Cmd.INFO_REQ,
                             {"caps": str(self.sink_pad.caps or "")})
                cmd, meta, _ = recv_message(sock)
                if cmd is not Cmd.INFO_APPROVE:
                    raise ConnectionError(f"server denied connection: {meta}")
                return sock
            except (OSError, QueryProtocolError, ConnectionError) as e:
                last = e
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
        raise ConnectionError(f"no reachable server: {last}")

    def _ensure_conn(self) -> socket.socket:
        if self._sock is None:
            retries = int(self.max_request_retry)
            last: Optional[Exception] = None
            for attempt in range(max(retries, 1)):
                try:
                    self._sock = self._connect()
                    return self._sock
                except (ConnectionError, OSError) as e:
                    last = e
                    time.sleep(min(0.2 * (attempt + 1), 1.0))
            raise ConnectionError(f"tensor_query_client: connect failed: {last}")
        return self._sock

    def start(self) -> None:
        self._caps_out_sent = False

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- negotiation --------------------------------------------------------- #
    def on_caps(self, pad: Pad, caps: Caps) -> None:
        pad.caps = caps
        # result stream is shape-dynamic from the client's viewpoint: declare
        # flexible; static caps could be fetched from the server in future
        self.send_caps_all(Caps.tensors(format=TensorFormat.FLEXIBLE))

    # -- dataflow ------------------------------------------------------------- #
    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        meta, payload = buffer_to_payload(buf, sparse=bool(self.sparse))
        for attempt in range(max(int(self.max_request_retry), 1)):
            try:
                sock = self._ensure_conn()
                send_message(sock, Cmd.DATA, meta, payload)
                cmd, rmeta, rpayload = recv_message(sock)
                if cmd is Cmd.ERROR:
                    raise QueryProtocolError(rmeta.get("error", "server error"))
                if cmd is not Cmd.RESULT:
                    raise QueryProtocolError(f"unexpected reply {cmd}")
                out = payload_to_buffer(rmeta, rpayload)
                out.pts, out.duration, out.offset = buf.pts, buf.duration, buf.offset
                return self.push(out)
            except (ConnectionError, OSError, QueryProtocolError) as e:
                log.warning("query attempt %d failed: %s", attempt + 1, e)
                self.stop()  # drop connection, retry fresh
        raise ConnectionError("tensor_query_client: request failed after retries")
