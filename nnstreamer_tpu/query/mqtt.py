"""MQTT 3.1.1 wire protocol: packet codec, minimal broker, client, SNTP.

Reference: gst/mqtt/ — mqttsink/mqttsrc publish GStreamer buffers through a
real MQTT broker (paho-mqtt-c), prepending a fixed 1024-byte
``GstMQTTMessageHdr`` (mqttcommon.h:29-63) to every message and timestamping
with an NTP-derived Unix epoch (ntputil.c ``ntputil_get_epoch``).

This module speaks genuine **MQTT 3.1.1 (protocol level 4)** frames —
CONNECT/CONNACK, SUBSCRIBE/SUBACK (with ``+``/``#`` wildcards),
PUBLISH (QoS 0), UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP, DISCONNECT — so
the elements interoperate with any standard broker (mosquitto, EMQX, …);
``MqttBroker`` is a built-in spec-subset broker for tests and single-host
deployments.  ``MessageHdr`` reproduces the reference header's exact binary
layout (same offsets, 1024 bytes) so an upstream subscriber can parse our
messages' metadata.  ``ntp_epoch_us`` is a real SNTP client with the
reference's conversion semantics (µs since Unix epoch, 1900→1970 delta).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.log import logger
from ..graph.element import join_or_warn
from .protocol import recv_exact as _recv_exact

log = logger("mqtt")

# -- packet types (MQTT 3.1.1 §2.2.1) --------------------------------------- #
CONNECT, CONNACK = 1, 2
PUBLISH = 3
PUBACK = 4
SUBSCRIBE, SUBACK = 8, 9
UNSUBSCRIBE, UNSUBACK = 10, 11
PINGREQ, PINGRESP = 12, 13
DISCONNECT = 14

PROTOCOL_NAME = b"MQTT"
PROTOCOL_LEVEL = 4  # 3.1.1


# --------------------------------------------------------------------------- #
# primitives
# --------------------------------------------------------------------------- #

def encode_remaining_length(n: int) -> bytes:
    """Variable-length remaining-length field (§2.2.3, 128-base varint)."""
    if n < 0 or n > 268_435_455:
        raise ValueError(f"remaining length out of range: {n}")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _utf8_field(s: bytes) -> bytes:
    if len(s) > 0xFFFF:
        raise ValueError("utf8 field too long")
    return struct.pack(">H", len(s)) + s


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_remaining_length(len(body)) + body


# --------------------------------------------------------------------------- #
# encoders
# --------------------------------------------------------------------------- #

def encode_connect(client_id: str, keep_alive: int = 60,
                   clean_session: bool = True) -> bytes:
    flags = 0x02 if clean_session else 0x00
    body = (_utf8_field(PROTOCOL_NAME) + bytes([PROTOCOL_LEVEL, flags])
            + struct.pack(">H", keep_alive) + _utf8_field(client_id.encode()))
    return _packet(CONNECT, 0, body)


def encode_connack(session_present: bool = False, return_code: int = 0) -> bytes:
    return _packet(CONNACK, 0, bytes([1 if session_present else 0, return_code]))


def encode_publish(topic: str, payload: bytes, qos: int = 0,
                   retain: bool = False, packet_id: int = 0) -> bytes:
    flags = (qos << 1) | (1 if retain else 0)
    body = _utf8_field(topic.encode())
    if qos > 0:
        body += struct.pack(">H", packet_id)
    return _packet(PUBLISH, flags, body + payload)


def encode_subscribe(packet_id: int, topics: Sequence[Tuple[str, int]]) -> bytes:
    body = struct.pack(">H", packet_id)
    for topic, qos in topics:
        body += _utf8_field(topic.encode()) + bytes([qos])
    return _packet(SUBSCRIBE, 0x2, body)  # reserved flags 0010 (§3.8.1)


def encode_suback(packet_id: int, return_codes: Sequence[int]) -> bytes:
    return _packet(SUBACK, 0, struct.pack(">H", packet_id) + bytes(return_codes))


def encode_puback(packet_id: int) -> bytes:
    return _packet(PUBACK, 0, struct.pack(">H", packet_id))


def encode_unsubscribe(packet_id: int, topics: Sequence[str]) -> bytes:
    body = struct.pack(">H", packet_id)
    for t in topics:
        body += _utf8_field(t.encode())
    return _packet(UNSUBSCRIBE, 0x2, body)


def encode_unsuback(packet_id: int) -> bytes:
    return _packet(UNSUBACK, 0, struct.pack(">H", packet_id))


def encode_pingreq() -> bytes:
    return _packet(PINGREQ, 0, b"")


def encode_pingresp() -> bytes:
    return _packet(PINGRESP, 0, b"")


def encode_disconnect() -> bytes:
    return _packet(DISCONNECT, 0, b"")


# --------------------------------------------------------------------------- #
# decoders
# --------------------------------------------------------------------------- #

#: mid-frame read budget once a packet's first byte has arrived: a frame
#: must either complete or the connection is declared broken — a short poll
#: timeout must never tear a partially-read frame (stream desync)
FRAME_TIMEOUT = 30.0


def read_packet(sock: socket.socket,
                first: Optional[int] = None) -> Tuple[int, int, bytes]:
    """Read one MQTT control packet → (type, flags, body). ``first`` is the
    already-consumed fixed-header byte when the caller polled for it."""
    if first is None:
        first = _recv_exact(sock, 1)[0]
    ptype, flags = first >> 4, first & 0x0F
    mult, length = 1, 0
    for _ in range(4):
        digit = _recv_exact(sock, 1)[0]
        length += (digit & 0x7F) * mult
        if not digit & 0x80:
            break
        mult *= 128
    else:
        raise ValueError("malformed remaining length")
    body = _recv_exact(sock, length) if length else b""
    return ptype, flags, body


def _take_utf8(body: bytes, off: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from(">H", body, off)
    off += 2
    return body[off:off + n], off + n


def parse_connect(body: bytes) -> Dict[str, Any]:
    name, off = _take_utf8(body, 0)
    if name != PROTOCOL_NAME:
        raise ValueError(f"not an MQTT 3.1.1 CONNECT (protocol {name!r})")
    level, flags = body[off], body[off + 1]
    (keep_alive,) = struct.unpack_from(">H", body, off + 2)
    client_id, off = _take_utf8(body, off + 4)
    return {"level": level, "clean_session": bool(flags & 0x02),
            "keep_alive": keep_alive, "client_id": client_id.decode()}


def parse_publish(flags: int, body: bytes) -> Tuple[str, bytes, int, int]:
    """→ (topic, payload, qos, packet_id) — packet_id 0 for QoS 0."""
    topic, off = _take_utf8(body, 0)
    qos = (flags >> 1) & 0x3
    packet_id = 0
    if qos > 0:
        (packet_id,) = struct.unpack_from(">H", body, off)
        off += 2
    return topic.decode(), body[off:], qos, packet_id


def parse_subscribe(body: bytes) -> Tuple[int, List[Tuple[str, int]]]:
    (packet_id,) = struct.unpack_from(">H", body, 0)
    off, topics = 2, []
    while off < len(body):
        t, off = _take_utf8(body, off)
        topics.append((t.decode(), body[off]))
        off += 1
    return packet_id, topics


def parse_unsubscribe(body: bytes) -> Tuple[int, List[str]]:
    (packet_id,) = struct.unpack_from(">H", body, 0)
    off, topics = 2, []
    while off < len(body):
        t, off = _take_utf8(body, off)
        topics.append(t.decode())
    return packet_id, topics


def topic_matches(filt: str, name: str) -> bool:
    """MQTT topic-filter matching with ``+`` (one level) and ``#`` (tail)."""
    fparts, nparts = filt.split("/"), name.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(nparts):
            return False
        if fp != "+" and fp != nparts[i]:
            return False
    return len(fparts) == len(nparts)


# --------------------------------------------------------------------------- #
# GstMQTTMessageHdr — reference-exact binary layout (mqttcommon.h:29-63)
# --------------------------------------------------------------------------- #

HDR_LEN = 1024            # GST_MQTT_LEN_MSG_HDR
MAX_CAPS_LEN = 512        # GST_MQTT_MAX_LEN_GST_CAPS_STR
MAX_NUM_MEMS = 16         # GST_MQTT_MAX_NUM_MEMS

#: C layout: guint num_mems; [4-byte alignment pad]; gsize size_mems[16];
#: gint64 base_time_epoch; gint64 sent_time_epoch; GstClockTime duration,
#: dts, pts; gchar gst_caps_str[512]; zero-padded to 1024 bytes.
_HDR = struct.Struct("<I4x16QqqQQQ512s")
CLOCK_NONE_U64 = 0xFFFFFFFFFFFFFFFF  # GST_CLOCK_TIME_NONE


@dataclass
class MessageHdr:
    num_mems: int = 0
    size_mems: Tuple[int, ...] = ()
    base_time_epoch: int = 0   # µs, Unix epoch (reference semantics)
    sent_time_epoch: int = 0   # µs
    duration: Optional[int] = None  # ns (GstClockTime)
    dts: Optional[int] = None
    pts: Optional[int] = None
    caps_str: str = ""

    def pack(self) -> bytes:
        if self.num_mems > MAX_NUM_MEMS or len(self.size_mems) > MAX_NUM_MEMS:
            raise ValueError(
                f"{self.num_mems} memories exceed the header's "
                f"GST_MQTT_MAX_NUM_MEMS={MAX_NUM_MEMS}")
        sizes = list(self.size_mems)
        sizes += [0] * (MAX_NUM_MEMS - len(sizes))
        caps = self.caps_str.encode()[:MAX_CAPS_LEN - 1]
        body = _HDR.pack(
            self.num_mems, *sizes,
            self.base_time_epoch, self.sent_time_epoch,
            CLOCK_NONE_U64 if self.duration is None else self.duration,
            CLOCK_NONE_U64 if self.dts is None else self.dts,
            CLOCK_NONE_U64 if self.pts is None else self.pts,
            caps)
        return body + b"\x00" * (HDR_LEN - len(body))

    @classmethod
    def unpack(cls, data: bytes) -> "MessageHdr":
        if len(data) < HDR_LEN:
            raise ValueError(f"MQTT message header truncated: {len(data)}")
        vals = _HDR.unpack_from(data, 0)
        num = vals[0]
        if num > MAX_NUM_MEMS:
            raise ValueError(f"num_mems {num} exceeds {MAX_NUM_MEMS}")
        sizes = vals[1:17]
        dur, dts, pts = vals[19], vals[20], vals[21]
        caps = vals[22].split(b"\x00", 1)[0].decode(errors="replace")
        return cls(num_mems=num, size_mems=tuple(sizes[:num]),
                   base_time_epoch=vals[17], sent_time_epoch=vals[18],
                   duration=None if dur == CLOCK_NONE_U64 else dur,
                   dts=None if dts == CLOCK_NONE_U64 else dts,
                   pts=None if pts == CLOCK_NONE_U64 else pts,
                   caps_str=caps)


# --------------------------------------------------------------------------- #
# SNTP (ntputil.c ntputil_get_epoch semantics)
# --------------------------------------------------------------------------- #

NTP_DELTA = 2_208_988_800  # seconds 1900→1970 (NTPUTIL_TIMESTAMP_DELTA)
NTP_DEFAULT = ("pool.ntp.org", 123)


def ntp_epoch_us(hosts: Sequence[Tuple[str, int]] = (),
                 timeout: float = 2.0) -> int:
    """Unix-epoch µs from the first reachable NTP server (48-byte SNTP
    mode-3 query; transmit timestamp at offset 40, converted exactly as the
    reference: (sec − 1900→1970 delta)·1e6 + frac/2³²·1e6).  Raises
    OSError if no server answers."""
    candidates = list(hosts) or [NTP_DEFAULT]
    last_err: Optional[Exception] = None
    for host, port in candidates:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.settimeout(timeout)
            pkt = bytearray(48)
            pkt[0] = 0x1B  # LI=0 VN=3 Mode=3 (client)
            sock.sendto(bytes(pkt), (host, int(port)))
            data, _ = sock.recvfrom(48)
            if len(data) < 48:
                raise OSError("short NTP response")
            # SNTP (RFC 4330) reply parsing: the pack side lives on the
            # NTP server, not in this codebase
            # nnslint: disable=wire/struct-format
            sec, frac = struct.unpack_from(">II", data, 40)
            if sec <= NTP_DELTA:
                raise OSError(f"NTP transmit timestamp invalid: {sec}")
            return ((sec - NTP_DELTA) * 1_000_000
                    + int(frac / 4294967295.0 * 1_000_000))
        except OSError as e:
            last_err = e
        finally:
            sock.close()
    raise OSError(f"no NTP server reachable: {last_err}")


def get_epoch_us(ntp_hosts: Optional[Sequence[Tuple[str, int]]] = None) -> int:
    """Publisher clock: NTP when hosts are configured (falling back on
    failure), else the system real-time clock (the reference's
    ``default_mqtt_get_unix_epoch`` ≙ g_get_real_time)."""
    if ntp_hosts:
        try:
            return ntp_epoch_us(ntp_hosts)
        except OSError as e:
            log.warning("NTP sync failed (%s); using system clock", e)
    return time.time_ns() // 1000


# --------------------------------------------------------------------------- #
# broker
# --------------------------------------------------------------------------- #

class MqttBroker:
    """Minimal MQTT 3.1.1 broker: CONNECT handshake, QoS-0 fanout with
    ``+``/``#`` wildcard subscriptions, ping, unsubscribe. Accepts any
    spec-conforming client."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883):
        self._subs: List[Tuple[str, socket.socket]] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        #: per-subscriber write locks: concurrent publishers must not
        #: interleave frame bytes on one subscriber socket
        self._wlocks: Dict[int, threading.Lock] = {}  # guarded-by: _lock
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._listener.settimeout(0.2)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MqttBroker":
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="mqtt-broker")
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # Nagle + delayed ACK stalls small PUBLISH forwards ~40 ms
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        try:
            ptype, _, body = read_packet(conn)
            if ptype != CONNECT:
                return
            info = parse_connect(body)
            if info["level"] != PROTOCOL_LEVEL:
                conn.sendall(encode_connack(return_code=0x01))  # bad version
                return
            conn.sendall(encode_connack())
            while not self._stop.is_set():
                ptype, flags, body = read_packet(conn)
                if ptype == PUBLISH:
                    topic, payload, qos, pid = parse_publish(flags, body)
                    if qos == 1:
                        conn.sendall(encode_puback(pid))
                    self._fanout(topic, payload)
                elif ptype == SUBSCRIBE:
                    pid, topics = parse_subscribe(body)
                    with self._lock:
                        self._subs.extend((t, conn) for t, _q in topics)
                    conn.sendall(encode_suback(pid, [0] * len(topics)))
                elif ptype == UNSUBSCRIBE:
                    pid, topics = parse_unsubscribe(body)
                    with self._lock:
                        self._subs = [
                            (t, c) for t, c in self._subs
                            if not (c is conn and t in topics)]
                    conn.sendall(encode_unsuback(pid))
                elif ptype == PINGREQ:
                    conn.sendall(encode_pingresp())
                elif ptype == DISCONNECT:
                    return
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                self._subs = [(t, c) for t, c in self._subs if c is not conn]
                self._wlocks.pop(id(conn), None)
            try:
                conn.close()
            except OSError:
                pass

    def _fanout(self, topic: str, payload: bytes) -> None:
        with self._lock:
            targets = [c for t, c in self._subs if topic_matches(t, topic)]
            wlocks = {id(c): self._wlocks.setdefault(id(c), threading.Lock())
                      for c in targets}
        frame = encode_publish(topic, payload)
        dead = []
        for c in dict.fromkeys(targets):  # de-dupe, keep order
            try:
                with wlocks[id(c)]:
                    c.sendall(frame)
            except OSError:
                dead.append(c)
        if dead:
            with self._lock:
                self._subs = [(t, c) for t, c in self._subs if c not in dead]
                for c in dead:
                    self._wlocks.pop(id(c), None)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # join the accept thread: its (timeout-bounded) accept() keeps
        # the kernel LISTEN socket alive past close(), so an immediate
        # broker restart on the same port races EADDRINUSE without this
        t = self._thread
        if t is not None and t is not threading.current_thread():
            join_or_warn(t, "mqtt-broker", timeout=2.0)
        self._thread = None


# --------------------------------------------------------------------------- #
# client
# --------------------------------------------------------------------------- #

class MqttClient:
    """Small synchronous MQTT 3.1.1 client (QoS 0) for the pub/sub
    elements and tests; works against any 3.1.1 broker."""

    def __init__(self, host: str, port: int, client_id: str,
                 keep_alive: int = 60, timeout: float = 5.0):
        self.keep_alive = int(keep_alive)
        self.sock = socket.create_connection((host, int(port)), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(encode_connect(client_id, self.keep_alive))
        ptype, _, body = read_packet(self.sock)
        if ptype != CONNACK or len(body) < 2 or body[1] != 0:
            raise ConnectionError(f"MQTT CONNECT refused: {body!r}")
        self._packet_id = 0
        self._last_send = time.monotonic()

    def _sendall(self, data: bytes) -> None:
        self.sock.sendall(data)
        self._last_send = time.monotonic()

    def _keepalive_tick(self) -> None:
        """§3.1.2.10: the broker may drop a client silent for 1.5×
        keep-alive; send PINGREQ when more than half the interval has
        passed without any control packet from us (receiving doesn't
        count)."""
        if self.keep_alive > 0 and \
                time.monotonic() - self._last_send > self.keep_alive / 2:
            self._sendall(encode_pingreq())

    def _next_id(self) -> int:
        self._packet_id = (self._packet_id % 0xFFFF) + 1
        return self._packet_id

    def publish(self, topic: str, payload: bytes) -> None:
        self._sendall(encode_publish(topic, payload))

    def subscribe(self, *topics: str) -> None:
        pid = self._next_id()
        self.sock.sendall(encode_subscribe(pid, [(t, 0) for t in topics]))
        ptype, _, body = read_packet(self.sock)
        if ptype != SUBACK:
            raise ConnectionError(f"expected SUBACK, got type {ptype}")
        (rid,) = struct.unpack_from(">H", body, 0)
        if rid != pid or any(rc == 0x80 for rc in body[2:]):
            raise ConnectionError(f"SUBSCRIBE rejected: {body!r}")

    def recv_publish(self, timeout: Optional[float] = None
                     ) -> Optional[Tuple[str, bytes]]:
        """Next PUBLISH (answering pings in between); None on timeout.
        The timeout applies between frames only — once a frame's first
        byte arrives the rest reads under FRAME_TIMEOUT, so a short poll
        interval cannot desync the stream mid-packet."""
        while True:
            self._keepalive_tick()
            self.sock.settimeout(timeout)
            try:
                first = _recv_exact(self.sock, 1)[0]
            except socket.timeout:
                return None
            self.sock.settimeout(FRAME_TIMEOUT)
            ptype, flags, body = read_packet(self.sock, first)
            if ptype == PUBLISH:
                topic, payload, _qos, _pid = parse_publish(flags, body)
                return topic, payload
            if ptype == PINGRESP:
                continue  # answer to our keep-alive PINGREQ

    def ping(self) -> bool:
        self._sendall(encode_pingreq())
        ptype, _, _ = read_packet(self.sock)
        return ptype == PINGRESP

    def close(self) -> None:
        try:
            self.sock.sendall(encode_disconnect())
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
