"""query-hybrid: broker-based server discovery.

Reference: gst/nnstreamer/tensor_query/tensor_query_hybrid.c/.h (:25-110):
servers publish "<topic> → (host, port)" to an MQTT broker; clients subscribe
to get the node list and fail over between nodes.

The reference requires an external MQTT broker; to stay dependency-free this
ships a tiny built-in TCP name service (``DiscoveryBroker``) speaking
line-JSON, with the same register/discover contract. If paho-mqtt is present
an MQTT-backed implementation can be swapped in via the same functions.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Dict, List, Optional, Tuple

from ..graph.element import join_or_warn


class DiscoveryBroker:
    """Line-JSON TCP name service: {"op":"register","topic":t,"host":h,"port":p}
    / {"op":"unregister",...} / {"op":"discover","topic":t} → {"nodes":[[h,p]]}."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5300):
        self._registry: Dict[str, List[Tuple[str, int]]] = {}
        self._lock = threading.Lock()
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                for line in self.rfile:
                    try:
                        msg = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    reply = broker._handle(msg)
                    self.wfile.write((json.dumps(reply) + "\n").encode())

        self._server = socketserver.ThreadingTCPServer((host, port), Handler,
                                                       bind_and_activate=False)
        self._server.allow_reuse_address = True
        self._server.server_bind()
        self._server.server_activate()
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _handle(self, msg: dict) -> dict:
        op = msg.get("op")
        topic = str(msg.get("topic", ""))
        with self._lock:
            if op == "register":
                node = (msg["host"], int(msg["port"]))
                self._registry.setdefault(topic, [])
                if node not in self._registry[topic]:
                    self._registry[topic].append(node)
                return {"ok": True}
            if op == "unregister":
                node = (msg["host"], int(msg["port"]))
                nodes = self._registry.get(topic, [])
                if node in nodes:
                    nodes.remove(node)
                return {"ok": True}
            if op == "discover":
                return {"ok": True,
                        "nodes": list(self._registry.get(topic, []))}
        return {"ok": False, "error": f"bad op {op!r}"}

    def start(self) -> "DiscoveryBroker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="query-broker")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        # join between shutdown() and server_close(): serve_forever may
        # still be inside its poll when close() pulls the socket away,
        # and the leaked thread then outlives the broker object
        t = self._thread
        if t is not None and t is not threading.current_thread():
            join_or_warn(t, "query-broker", timeout=2.0)
        self._thread = None
        self._server.server_close()


def _rpc(host: str, port: int, msg: dict, timeout: float = 5.0) -> dict:
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall((json.dumps(msg) + "\n").encode())
        data = sock.makefile().readline()
    return json.loads(data or "{}")


def register_node(topic: str, host: str, port: int,
                  broker_host: str = "127.0.0.1", broker_port: int = 5300) -> bool:
    return _rpc(broker_host, broker_port,
                {"op": "register", "topic": topic, "host": host,
                 "port": port}).get("ok", False)


def unregister_node(topic: str, host: str, port: int,
                    broker_host: str = "127.0.0.1", broker_port: int = 5300) -> bool:
    return _rpc(broker_host, broker_port,
                {"op": "unregister", "topic": topic, "host": host,
                 "port": port}).get("ok", False)


def discover(topic: str, broker_host: str = "127.0.0.1",
             broker_port: int = 5300) -> List[Tuple[str, int]]:
    nodes = _rpc(broker_host, broker_port,
                 {"op": "discover", "topic": topic}).get("nodes", [])
    return [(h, int(p)) for h, p in nodes]
