"""tensor_query server side: serversrc / serversink elements.

Reference: gst/nnstreamer/tensor_query/tensor_query_serversrc.c /
_serversink.c — a server *pipeline* whose source is remote client frames and
whose sink returns results, paired by ``id``. Usage:

    server pipeline:  tensor_query_serversrc id=0 port=5001 !
                      tensor_filter ... ! tensor_query_serversink id=0

The listener accepts N concurrent clients; each DATA message is pushed into
the pipeline (buffer.meta carries the connection id) and the matching
serversink routes the RESULT back on the same connection. This is where TPU
pod offload plugs in: the server pipeline's filter may run mesh-sharded
(parallel.make_sharded_infer_step) so one host fans frames over its slice.
"""

from __future__ import annotations

import socket
import threading
import time
import weakref
from typing import Any, Dict, Optional

from ..core.buffer import Buffer
from ..core.log import logger
from ..core.types import Caps, TensorsConfig, TensorsInfo
from ..graph.element import (
    Element,
    FlowReturn,
    Pad,
    join_or_warn,
    register_element,
)
from ..graph.pipeline import SourceElement
from ..obs import events as _events
from ..obs import fleet as _fleet
from ..obs import health as _health
from ..obs import metrics as _obs
from ..obs import tracing as _tracing
from ..resilience import policy as _rp
from .protocol import (
    Cmd,
    QueryProtocolError,
    buffer_to_payload,
    payload_to_buffer,
    recv_message,
    send_message,
)

log = logger("query")

_pairs_lock = threading.Lock()
_server_pairs: Dict[int, "TensorQueryServerSrc"] = {}

#: disaggregated-serving import point (serving/disagg.py
#: register_import_target installs/clears this): called as
#: ``hook(meta, payload, deadline) -> pages_imported`` for every
#: ``KV_PAGE_XFER`` frame a serversrc receives; ``deadline`` is already
#: re-anchored on this host's clock (like DATA). None — the default —
#: answers the sender with ERROR: a backend that never registered a
#: page-import target must reject transfers loudly, not absorb them.
#: Disabled cost: one module-global load per non-data frame.
KV_IMPORT_HOOK = None


def handle_kv_page_xfer(conn: socket.socket, meta: Dict[str, Any],
                        payload: bytes, hook: Any = None) -> None:
    """One KV_PAGE_XFER frame: re-anchor the wire deadline, hand the
    page document to the import target, and answer RESULT (pages
    spliced) or ERROR (no target / expired / rejected). Shared by the
    serversrc dispatch (which uses the process-global KV_IMPORT_HOOK)
    and serving/disagg.py's worker loop (which binds its own engine's
    hook) so both endpoints speak identical transfer semantics."""
    hook = hook if hook is not None else KV_IMPORT_HOOK
    dl = _rp.Deadline.from_wire(meta.get(_rp.WIRE_KEY))
    if hook is None:
        send_message(conn, Cmd.ERROR,
                     {"error": "no KV page-import target registered"})
        return
    if dl is not None and dl.expired():
        # the transfer outlived its request budget in flight: splicing
        # now would pin pages for a result nobody is waiting for
        send_message(conn, Cmd.ERROR,
                     {"error": "KV page transfer deadline expired"})
        return
    try:
        n = int(hook(meta, payload, dl))
    except (ValueError, RuntimeError) as e:
        send_message(conn, Cmd.ERROR, {"error": f"kv import rejected: {e}"})
        return
    send_message(conn, Cmd.RESULT, {"kv_imported": n})


def wait_bound_port(src: "TensorQueryServerSrc",
                    timeout_s: float = 10.0) -> int:
    """Block until a started serversrc has bound its listener (it binds in
    negotiate() on the src thread) and return the real port. Raises
    RuntimeError — naming the element — on timeout, e.g. when negotiation
    failed, instead of the bare AttributeError a direct ``src.bound_port``
    read would produce."""
    deadline = time.monotonic() + timeout_s
    while not hasattr(src, "bound_port"):
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"{src.name}: serversrc did not bind within {timeout_s}s "
                "(negotiation failed? check the pipeline bus)")
        time.sleep(0.02)
    return src.bound_port


@register_element
class TensorQueryServerSrc(SourceElement):
    ELEMENT_NAME = "tensor_query_serversrc"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.host = "0.0.0.0"
        self.port = 5001
        self.id = 0
        self.caps: Optional[Caps] = None   # declared stream type
        self.dims: Optional[str] = None
        self.types: Optional[str] = None
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._conns: Dict[int, socket.socket] = {}  # guarded-by: _lock
        self._conn_seq = 0  # guarded-by: _lock
        self._inbox: "__import__('queue').Queue" = None
        self._threads = []  # guarded-by: _lock
        # server-side offload telemetry (message/byte counts live at the
        # protocol layer): accepted connections, and inbox depth read at
        # collection time
        reg = _obs.registry()
        self._m_conns = reg.counter(
            "nnstpu_query_connections_total",
            "Client connections accepted by the server listener",
            ("element",)).labels(self.name)
        reg.gauge(
            "nnstpu_query_inbox_depth",
            "Frames queued between the server listener and its pipeline",
            ("element",)).labels(self.name).set_function(
                lambda: self._inbox.qsize() if self._inbox is not None
                else 0)
        # health component: connection count + inbox depth, weakref so the
        # registry never pins a retired listener. A no-op while health is
        # off (shared NOOP_COMPONENT, zero per-frame cost).
        ref = weakref.ref(self)
        self._hc = _health.component(
            f"query.server:{self.name}", kind="query",
            probe=lambda: (lambda s: None if s is None else
                           {"connections": len(s._conns),
                            "inbox_depth": s._inbox.qsize()
                            if s._inbox is not None else 0})(ref()),
            attrs={"element": self.name})

    # -- lifecycle ---------------------------------------------------------- #
    def negotiate(self) -> Caps:
        import queue as _q

        if self.caps is None:
            if self.dims and self.types:
                self.caps = Caps.tensors(
                    TensorsConfig(TensorsInfo.from_strings(self.dims, self.types)))
            else:
                raise ValueError("tensor_query_serversrc needs caps or dims/types")
        self._inbox = _q.Queue(maxsize=64)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, int(self.port)))
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        with _pairs_lock:
            _server_pairs[int(self.id)] = self
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"qsrv-accept:{self.name}")
        # register BEFORE start: stop() snapshots _threads under _lock,
        # so a started-but-unregistered worker would be unjoinable
        with self._lock:
            self._threads.append(t)
        t.start()
        self.bound_port = self._listener.getsockname()[1]
        return self.caps

    def _accept_loop(self) -> None:
        while not self._stop_flag.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # without NODELAY, Nagle + the client's delayed ACK holds each
            # small RESULT write ~40 ms — measured 65 ms/frame round trips
            # on localhost vs sub-ms with it
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._m_conns.inc()
            self._hc.beat()
            self._hc.count("accept")
            with self._lock:
                self._conn_seq += 1
                cid = self._conn_seq
                self._conns[cid] = conn
            _events.record("query.accept",
                           f"{self.name}: accepted client {cid} from "
                           f"{addr[0]}:{addr[1]}",
                           element=self.name, client=cid)
            t = threading.Thread(target=self._client_loop, args=(cid, conn),
                                 daemon=True, name=f"qsrv-conn{cid}")
            with self._lock:
                self._threads.append(t)
            t.start()

    def _client_loop(self, cid: int, conn: socket.socket) -> None:
        try:
            while not self._stop_flag.is_set():
                cmd, meta, payload = recv_message(conn)
                if cmd is Cmd.INFO_REQ:
                    # approve iff declared caps are compatible (REQUEST_INFO/
                    # RESPOND_APPROVE handshake, tensor_query_common.h:42-51).
                    # The fleet instance id joins this endpoint to its
                    # pushed health/queue-depth snapshots, so a router
                    # can place by live load instead of blind rotation.
                    peer_caps = str(meta.get("caps") or "")
                    peer_mt = peer_caps.split("(", 1)[0].strip()
                    if peer_mt and self.caps is not None \
                            and peer_mt != self.caps.media_type:
                        # explicit deny beats letting the first DATA frame
                        # die on a decode error: the client sees the reason
                        # and its router can strike this backend cleanly
                        send_message(conn, Cmd.INFO_DENY,
                                     {"error": f"caps mismatch: server "
                                      f"streams {self.caps.media_type}, "
                                      f"client declared {peer_mt}",
                                      "caps": str(self.caps)})
                        continue
                    send_message(conn, Cmd.INFO_APPROVE,
                                 {"caps": str(self.caps), "client_id": cid,
                                  "instance": _fleet.default_instance()})
                elif cmd is Cmd.PING:
                    send_message(conn, Cmd.PONG, {})
                elif cmd is Cmd.DATA:
                    self._hc.beat()
                    buf = payload_to_buffer(meta, payload)
                    buf.meta["query_client_id"] = cid
                    sess = meta.get("session")
                    if sess is not None:
                        # session affinity key survives the wire so the
                        # serving layer can pin KV/prefix reuse to it
                        buf.meta["session"] = sess
                    dms = meta.get(_rp.WIRE_KEY)
                    if dms is not None:
                        # re-anchor the remaining budget on THIS host's
                        # monotonic clock (never compare peer clocks);
                        # downstream elements/engines shed if it expires
                        dl = _rp.Deadline.from_wire(dms)
                        if dl is not None:
                            _rp.set_deadline(buf, dl)
                    if _tracing.enabled():
                        # adopt the client's context so one trace spans
                        # both halves: the handling span parents every
                        # server-side pipeline.element span and is closed
                        # once the RESULT goes back out (send_result)
                        rctx = _tracing.ctx_from_wire(
                            meta.get(_tracing.TRACE_META_KEY))
                        if rctx is not None:
                            # wire-crossing trace: mark it so fleet push
                            # exports this half of the tree
                            _tracing.store().mark_export(rctx.trace_id)
                            span = _tracing.start_span(
                                "query.server_handle", parent=rctx,
                                attrs={"client": cid, "element": self.name})
                            if span.recording:
                                buf.meta[_tracing.CTX_META_KEY] = span.context
                                buf.meta[_tracing.ROOT_META_KEY] = span
                    self._inbox.put(buf)
                elif cmd is Cmd.OBS_PUSH:
                    # fleet telemetry piggyback: ingest when this process
                    # aggregates, drop otherwise; never a reply frame
                    _fleet.ingest_wire(meta, payload)
                elif cmd is Cmd.KV_PAGE_XFER:
                    # disaggregated serving: splice migrated KV pages
                    # into the registered engine's pool and answer
                    # RESULT/ERROR (serving/disagg.py owns the framing)
                    self._hc.beat()
                    handle_kv_page_xfer(conn, meta, payload)
                else:
                    send_message(conn, Cmd.ERROR,
                                 {"error": f"unexpected cmd {cmd}"})
        except (ConnectionError, QueryProtocolError, OSError) as e:
            log.debug("server conn %d closed: %s", cid, e)
        finally:
            with self._lock:
                self._conns.pop(cid, None)
            _events.record("query.disconnect",
                           f"{self.name}: client {cid} disconnected",
                           element=self.name, client=cid)
            try:
                conn.close()
            except OSError:
                pass

    def create(self) -> Optional[Buffer]:
        import queue as _q

        while not self._stop_flag.is_set():
            try:
                return self._inbox.get(timeout=0.1)
            except _q.Empty:
                continue
        return None

    def send_result(self, cid: int, buf: Buffer) -> bool:
        span = buf.meta.get(_tracing.ROOT_META_KEY, _tracing.NOOP_SPAN)
        with self._lock:
            conn = self._conns.get(cid)
        if conn is None:
            span.end()
            return False
        meta, payload = buffer_to_payload(buf)
        token = None
        if span.recording:
            # make the handling span current so the RESULT frame carries
            # the trace back to the client (send_message injects it);
            # needed explicitly because the async serversink drains from
            # its own thread, outside any instrumented chain
            token = _tracing._set_current(span.context)
        try:
            send_message(conn, Cmd.RESULT, meta, payload)
            return True
        except OSError as e:
            log.warning("result send to client %d failed: %s", cid, e)
            return False
        finally:
            if token is not None:
                _tracing._reset_current(token)
            span.end()

    def stop(self) -> None:
        super().stop()
        with _pairs_lock:
            _server_pairs.pop(int(self.id), None)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        # join the accept/connection workers: an accept still inside its
        # (timeout-bounded) syscall keeps the kernel LISTEN socket alive
        # past close(), so returning before it exits races an immediate
        # rebind of the same port with EADDRINUSE (server restart)
        cur = threading.current_thread()
        with self._lock:
            workers = list(self._threads)
            self._threads = []
        for t in workers:
            if t is not cur:
                join_or_warn(t, self.name, timeout=2.0)


@register_element
class TensorQueryServerSink(Element):
    """Routes results back to the paired serversrc connection.

    ``async_depth=N`` (default 1 = synchronous): keep up to N result
    buffers in flight between the filter and the wire. Each buffer's
    device→host readback is *prefetched* at chain time and materialized by
    a drain thread in order, so a TPU-resident filter output costs one
    overlapped transfer instead of one full device RTT per frame — the
    server-side half of pipelined query offload (client half:
    tensor_query_client ``async_depth``).
    """

    ELEMENT_NAME = "tensor_query_serversink"

    def __init__(self, name: Optional[str] = None, **props: Any):
        self.id = 0
        self.async_depth = 1
        super().__init__(name, **props)
        self.add_sink_pad(template=Caps.any_tensors())
        self._dq: "__import__('collections').deque" = None  # guarded-by: _cv
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._draining = False  # guarded-by: _cv

    def _route(self, buf: Buffer) -> None:
        with _pairs_lock:
            src = _server_pairs.get(int(self.id))
        if src is None:
            raise RuntimeError(
                f"tensor_query_serversink id={self.id}: no matching serversrc")
        cid = buf.meta.get("query_client_id")
        if cid is None:
            raise RuntimeError("buffer lost its query_client_id")
        src.send_result(cid, buf)

    def start(self) -> None:
        import collections

        # publish the fresh deque/flag under _cv: a chain() racing a
        # restart must never observe the new deque with the old flag
        with self._cv:
            self._dq = collections.deque()
            self._draining = True
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name=f"qsink:{self.name}")
        self._worker.start()

    def stop(self) -> None:
        with self._cv:
            self._draining = False
            self._cv.notify_all()
        w = self._worker
        if w is not None and w is not threading.current_thread():
            join_or_warn(w, self.name, timeout=5.0)
        self._worker = None

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._dq and self._draining:
                    self._cv.wait(0.1)
                if not self._dq and not self._draining:
                    return
                buf = self._dq[0]
            try:
                self._route(buf)
            except RuntimeError as e:
                self.post_error(str(e), exc=e)
                with self._cv:
                    # release any producer blocked on a full queue so its
                    # chain() returns ERROR promptly instead of spinning
                    # until an external stop() (mirrors TensorBatch's
                    # _quit_worker teardown)
                    self._draining = False
                    self._cv.notify_all()
                return
            finally:
                with self._cv:
                    # pop AFTER the send: the EOS drain (and therefore
                    # pipeline stop, which closes the client connections)
                    # must not race past a result still being written
                    self._dq.popleft()
                    self._cv.notify_all()

    def chain(self, pad: Pad, buf: Buffer) -> Optional[FlowReturn]:
        depth = int(self.async_depth or 1)
        if depth <= 1:
            self._route(buf)
            return FlowReturn.OK
        for m in buf.memories:
            m.prefetch()  # start the D2H now; drain materializes in order
        with self._cv:
            while len(self._dq) >= depth and self._draining:
                self._cv.wait(0.1)
            if not self._draining:
                return FlowReturn.ERROR
            self._dq.append(buf)
            self._cv.notify_all()
        return FlowReturn.OK

    def on_eos(self) -> None:
        deadline = time.monotonic() + 60
        with self._cv:
            while self._dq and self._draining and time.monotonic() < deadline:
                self._cv.wait(0.2)
