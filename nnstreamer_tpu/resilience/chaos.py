"""Deterministic chaos injection — seeded fault plans for the wire and
the graph.

Testing the resilience policies used to require hand-rolled socket
games (kill a server mid-recv, hope the timing lands). This harness
makes faults first-class and REPRODUCIBLE: a :class:`FaultPlan` is a
seeded schedule of drop/delay/corrupt/disconnect/kill faults, fired either
on the Nth matching call or probabilistically from a per-fault PRNG —
the same seed always yields the same schedule, independent of wall
clock and (per target) of thread interleaving.

Injection points (the hosting modules own the hook variables so this
module is never imported on the hot path):

* ``query.protocol.CHAOS_HOOK`` — called at the top of
  ``send_message`` (target ``"send"``) and after each frame in
  ``recv_message`` (target ``"recv"``); returning ``None`` drops the
  frame, raising propagates into the caller's error handling.
* ``graph.element.CHAOS_CHAIN_HOOK`` — called by ``Pad.push`` before
  the peer's chain (target ``"chain:<element-name>"``); truthy return
  drops the buffer (the graph's legal drop semantics).

Both hooks are module globals that are ``None`` unless a plan is
installed — the disabled cost is one global load + ``is None`` check,
the same zero-overhead contract as tracing. Enable via
:func:`install`, or the ``NNS_TPU_CHAOS`` environment variable (a JSON
plan, honored by ``nns-launch``; see :func:`plan_from_env`).
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core.log import logger
from ..obs import events as _events
from ..obs import metrics as _obs

log = logger("chaos")

#: environment variable carrying a JSON fault plan (nns-launch honors it)
ENV_VAR = "NNS_TPU_CHAOS"

KINDS = ("drop", "delay", "corrupt", "disconnect", "partition", "kill")

_INJECTED_TOTAL = _obs.registry().counter(
    "nnstpu_chaos_injected_total",
    "Faults fired by the installed fault plan", ("kind",))

#: endpoint -> kill handle for the ``kill`` fault kind: a launched
#: backend's pid (int), a Popen-like object exposing ``.pid``, or a
#: zero-arg callable (how tests SIGKILL an in-process worker shim).
#: A plain dict guarded by its own lock — registration happens at
#: launch/teardown time, never on the wire hot path, and the hook only
#: reads it after a fault already fired.
_KILL_TARGETS: Dict[str, Any] = {}
_KILL_LOCK = threading.Lock()


def register_kill_target(endpoint: str, target: Any) -> None:
    """Make ``endpoint`` killable by a planned ``kill`` fault.

    ``target`` is SIGKILLed when the fault fires: an int pid, an
    object with ``.pid`` (subprocess.Popen), or a zero-arg callable
    (in-process workers — tests register ``worker.kill``). Launchers
    register their children here so a chaos plan can crash exactly one
    backend of a routed set, no drain, no goodbye."""
    with _KILL_LOCK:
        _KILL_TARGETS[str(endpoint)] = target


def unregister_kill_target(endpoint: str) -> None:
    with _KILL_LOCK:
        _KILL_TARGETS.pop(str(endpoint), None)


def _do_kill(endpoint: Optional[str]) -> str:
    """SIGKILL the registered target for ``endpoint``; returns a
    human-readable note for the audit event. An unregistered endpoint
    is a no-op beyond the note — the fault still severs the frame, so
    the plan's schedule is unchanged either way."""
    with _KILL_LOCK:
        target = _KILL_TARGETS.get(str(endpoint))
    if target is None:
        return f"no kill target registered for {endpoint}"
    if callable(target):
        target()
        return f"killed in-process target for {endpoint}"
    pid = getattr(target, "pid", target)
    os.kill(int(pid), signal.SIGKILL)
    return f"SIGKILLed pid {int(pid)} ({endpoint})"


@dataclass
class Fault:
    """One fault rule inside a :class:`FaultPlan`.

    ``target`` is ``"send"`` / ``"recv"`` (the query wire; ``cmd``
    optionally restricts to one command name, e.g. ``"DATA"`` so the
    INFO handshake survives) or ``"chain:<element>"`` (a specific sink
    element; bare ``"chain"`` matches every element). ``endpoint``
    narrows a wire fault to one peer (``"host:port"`` as seen by the
    socket) — how a plan kills exactly one backend of a routed set.
    Fire selection: ``nth`` (an int or collection of ints, 1-based call
    numbers within the matching stream) is exact; otherwise ``p`` draws
    per matching call from the fault's own seeded PRNG. ``max_fires``
    caps total fires without disturbing the draw sequence.

    Kind ``partition`` is stateful: once its nth/p trigger fires, the
    fault latches and EVERY subsequent matching frame raises
    ConnectionError — one side of a network partition, not a one-shot
    disconnect. The latch counts as a single fire in the audit log.

    Kind ``kill`` SIGKILLs the backend behind the matched frame (the
    fault's ``endpoint`` names the victim; see
    :func:`register_kill_target`) and then raises ConnectionError —
    a planned crash with no drain and no goodbye, for the
    fleet/checkpoint restore acceptance tests. Subsequent frames to
    the dead endpoint fail naturally, so ``max_fires=1`` is the usual
    spelling.
    """

    kind: str
    target: str = "send"
    cmd: Optional[str] = None
    endpoint: Optional[str] = None
    nth: Any = None
    p: float = 0.0
    delay_s: float = 0.01
    max_fires: Optional[int] = None
    nth_set: frozenset = field(init=False, repr=False)

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.nth is None:
            self.nth_set = frozenset()
        elif isinstance(self.nth, int):
            self.nth_set = frozenset({self.nth})
        else:
            self.nth_set = frozenset(int(n) for n in self.nth)

    def matches(self, target: str, cmd: Optional[str],
                endpoint: Optional[str] = None) -> bool:
        if self.target == "chain":
            if not target.startswith("chain:"):
                return False
        elif self.target != target:
            return False
        if self.endpoint is not None and self.endpoint != endpoint:
            return False
        return self.cmd is None or self.cmd == cmd


class FaultPlan:
    """A seeded, deterministic schedule of faults.

    Each fault owns a PRNG seeded from ``(seed, fault_index)`` and a
    counter of *matching* calls, so its fire schedule is a pure function
    of the plan and the per-target call sequence — two plans built from
    the same spec make identical decisions (the determinism test pins
    this). ``fired`` is an audit log of every injection.
    """

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.seed = int(seed)
        self.faults = list(faults)
        self._lock = threading.Lock()
        self._counts = [0] * len(self.faults)
        self._fires = [0] * len(self.faults)
        # partition faults latch: once triggered they fire on every
        # subsequent matching frame until the plan is uninstalled
        self._latched = [False] * len(self.faults)
        self._latch_pending: List[Fault] = []
        # per-fault PRNG, seeded from (seed, index) mixed into one int
        # (tuple seeding is deprecated); large odd multiplier keeps
        # nearby seeds from producing overlapping streams
        self._rngs = [random.Random(self.seed * 1_000_003 + i)
                      for i in range(len(self.faults))]
        self.fired: List[Dict[str, Any]] = []

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "FaultPlan":
        """Build from a JSON-shaped dict:
        ``{"seed": 7, "faults": [{"kind": "drop", "target": "send",
        "cmd": "DATA", "p": 0.1}, ...]}``."""
        faults = [Fault(**f) for f in spec.get("faults", ())]
        return cls(faults, seed=int(spec.get("seed", 0)))

    def decide(self, target: str, cmd: Optional[str] = None,
               endpoint: Optional[str] = None) -> List[Fault]:
        """Advance the schedule one call at ``target``; returns the
        faults that fire on this call (usually zero or one)."""
        hits: List[Fault] = []
        with self._lock:
            for i, f in enumerate(self.faults):
                if not f.matches(target, cmd, endpoint):
                    continue
                if self._latched[i]:
                    # partition already triggered: fires silently on
                    # every matching frame (audited once, at the latch)
                    hits.append(f)
                    continue
                self._counts[i] += 1
                n = self._counts[i]
                if f.nth_set:
                    fire = n in f.nth_set
                elif f.p > 0.0:
                    # always draw so capped faults keep the sequence
                    fire = self._rngs[i].random() < f.p
                else:
                    fire = False
                if fire and (f.max_fires is None
                             or self._fires[i] < f.max_fires):
                    self._fires[i] += 1
                    if f.kind == "partition":
                        self._latched[i] = True
                        self._latch_pending.append(f)
                    self.fired.append({"kind": f.kind, "target": target,
                                       "cmd": cmd, "endpoint": endpoint,
                                       "call": n})
                    hits.append(f)
        return hits

    def heal(self) -> None:
        """Release every latched partition (the net heals); the rest of
        the schedule continues where it left off."""
        with self._lock:
            self._latched = [False] * len(self.faults)
            self._latch_pending.clear()

    def take_latch_notice(self, f: Fault) -> bool:
        """True exactly once per latch of ``f`` — lets the hook emit
        the partition event/log at the latch moment instead of on
        every subsequently blocked frame."""
        with self._lock:
            try:
                self._latch_pending.remove(f)
                return True
            except ValueError:
                return False


_ACTIVE: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    return _ACTIVE


def _corrupt(payload: bytes) -> bytes:
    """Deterministically damage a payload (first byte inverted) — enough
    to fail deserialization/checksums without hiding which frame it was."""
    if not payload:
        return payload
    return bytes([payload[0] ^ 0xFF]) + payload[1:]


def _fire(f: Fault, target: str, detail: str) -> None:
    _INJECTED_TOTAL.labels(f.kind).inc()
    log.warning("chaos: injected %s at %s (%s)", f.kind, target, detail)
    _events.record("chaos.inject",
                   f"injected {f.kind} at {target} ({detail})",
                   severity="warning", kind=f.kind, target=target)


def _wire_hook(direction: str, cmd: Any, meta: Dict[str, Any],
               payload: bytes,
               endpoint: Optional[str] = None) -> Optional[bytes]:
    """Installed as ``protocol.CHAOS_HOOK``. Returns the (possibly
    corrupted) payload, or None to drop the frame; raises
    ConnectionError for an injected disconnect or an active partition.
    ``endpoint`` is the socket's peer (``"host:port"``) when the
    protocol layer can resolve it — how endpoint-scoped faults single
    out one backend of a routed set."""
    plan = _ACTIVE
    if plan is None:
        return payload
    name = getattr(cmd, "name", str(cmd))
    for f in plan.decide(direction, name, endpoint):
        if f.kind == "partition":
            # frames keep dying while the partition holds, but the
            # event/log land once, at the latch; the counter tracks
            # every blackholed frame
            if plan.take_latch_notice(f):
                _fire(f, direction, f"cmd={name} endpoint={endpoint}")
            else:
                _INJECTED_TOTAL.labels(f.kind).inc()
            raise ConnectionError(
                f"chaos: partition active ({direction} {name} "
                f"endpoint={endpoint})")
        if f.kind == "kill":
            # kill -9 the backend BEHIND this frame (no drain, no
            # goodbye), then die like the severed connection the peer
            # would actually see. The fault's own endpoint wins over
            # the frame's — a recv-side plan can still name its victim
            note = _do_kill(f.endpoint or endpoint)
            _fire(f, direction, f"cmd={name} {note}")
            raise ConnectionError(
                f"chaos: backend killed ({direction} {name} "
                f"endpoint={f.endpoint or endpoint})")
        _fire(f, direction, f"cmd={name}" if endpoint is None
              else f"cmd={name} endpoint={endpoint}")
        if f.kind == "delay":
            time.sleep(f.delay_s)
        elif f.kind == "disconnect":
            raise ConnectionError(
                f"chaos: injected disconnect ({direction} {name})")
        elif f.kind == "corrupt":
            payload = _corrupt(payload)
        elif f.kind == "drop":
            return None
    return payload


def _poison_buffer(buf: Any) -> None:
    """Graph-side corrupt: silently wreck the buffer's first tensor
    *in place* (value-semantically — the TensorMemory is replaced, not
    mutated). Float dtypes become all-NaN, integer dtypes saturate to
    the dtype max, anything else goes constant-ones. Unlike the wire
    corrupt (which fails deserialization loudly), this is the quiet
    failure mode real accelerator bugs produce: data keeps flowing,
    wrong — exactly what obs/quality's NaN-storm and dead-output rules
    exist to catch."""
    import numpy as np

    from ..core.buffer import TensorMemory

    if not getattr(buf, "memories", None):
        return
    mem = buf.memories[0]
    arr = np.array(mem.host(), copy=True)
    if np.issubdtype(arr.dtype, np.floating) \
            or np.issubdtype(arr.dtype, np.complexfloating):
        arr[...] = np.nan
    elif np.issubdtype(arr.dtype, np.integer):
        arr[...] = np.iinfo(arr.dtype).max
    else:
        arr[...] = 1
    buf.memories[0] = TensorMemory(arr, info=mem.info)


def _chain_hook(element: str, buf: Any) -> bool:
    """Installed as ``element.CHAOS_CHAIN_HOOK``. True drops the
    buffer; delay sleeps in the pushing thread; corrupt NaN-poisons the
    buffer's first tensor and lets it flow on (see
    :func:`_poison_buffer`); disconnect/partition raise (the graph
    turns that into a bus error)."""
    plan = _ACTIVE
    if plan is None:
        return False
    target = f"chain:{element}"
    drop = False
    for f in plan.decide(target):
        _fire(f, target, f"pts={buf.pts}")
        if f.kind == "delay":
            time.sleep(f.delay_s)
        elif f.kind == "drop":
            drop = True
        elif f.kind == "corrupt":
            _poison_buffer(buf)
        else:
            raise RuntimeError(f"chaos: injected {f.kind} at {target}")
    return drop


def install(plan: FaultPlan) -> FaultPlan:
    """Activate a plan: point the protocol and graph hook globals at
    this module. Imports are lazy — an idle chaos module never touches
    the hot-path modules."""
    global _ACTIVE
    from ..graph import element as _element
    from ..query import protocol as _protocol

    _ACTIVE = plan
    _protocol.CHAOS_HOOK = _wire_hook
    _element.CHAOS_CHAIN_HOOK = _chain_hook
    _events.record("chaos.install",
                   f"fault plan installed (seed={plan.seed}, "
                   f"{len(plan.faults)} faults)", seed=plan.seed)
    return plan


def uninstall() -> None:
    """Deactivate: hooks back to None (the zero-overhead state)."""
    global _ACTIVE
    from ..graph import element as _element
    from ..query import protocol as _protocol

    _protocol.CHAOS_HOOK = None
    _element.CHAOS_CHAIN_HOOK = None
    _ACTIVE = None


def plan_from_env() -> Optional[FaultPlan]:
    """Parse :data:`ENV_VAR` into a plan (None when unset/invalid —
    a malformed plan is reported, never fatal: chaos must not be able
    to take a pipeline down by typo)."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    try:
        return FaultPlan.from_spec(json.loads(raw))
    except (ValueError, TypeError, KeyError) as e:
        log.warning("%s ignored (bad plan: %s)", ENV_VAR, e)
        return None
