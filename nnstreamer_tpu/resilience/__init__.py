"""Resilience layer: fault policies + deterministic chaos injection.

``policy`` owns the react-side primitives (RetryPolicy, RetryBudget,
CircuitBreaker, Deadline, shed/fallback accounting); ``chaos`` owns the
seeded fault-injection harness that makes those policies testable.
Import the submodules directly — ``chaos`` is intentionally NOT pulled
in here so merely importing a policy user (e.g. the query client) never
touches the wire/graph hook modules.
"""

from . import policy  # noqa: F401  (the package's stable surface)
