"""Fault policies — backoff, retry budgets, circuit breaking, deadlines.

The reference treats failure handling as a bounded retry counter
(``max-request-retry``, tensor_query_client.c:769-776) and leaves
degradation under partial failure to the application (the paper's §IV
"fault tolerance" is reconnect-only). This module is the react half of
the observe→react loop the obs stack (metrics/tracing/health/events)
opened: policies that decide WHEN to retry, when to stop trying, and
when work is no longer worth doing at all.

Pieces (wired through query/serving by their owners, not here):

* :class:`RetryPolicy` — exponential backoff with FULL jitter
  (delay ~ U(0, min(cap, base·mult^attempt))); jitter decorrelates the
  reconnect storms the health watchdog's storm rule exists to detect.
* :class:`RetryBudget` — a single attempt allowance shared by every
  loop on one request path. ``chain()`` and ``_ensure_conn()`` each
  owning a ``max_request_retry`` loop multiplied into retry² dials per
  frame; both now draw from one budget.
* :class:`CircuitBreaker` — closed/open/half-open with a bounded probe
  count, injectable clock for deterministic tests, state exposed as the
  ``nnstpu_resilience_breaker_state`` gauge and ``resilience.breaker_*``
  events.
* :class:`Deadline` — a point in LOCAL monotonic time carried in
  ``Buffer.meta[DEADLINE_META_KEY]``; on the wire it travels as
  *remaining milliseconds* (``WIRE_KEY``), so peers never compare
  foreign clock domains. Expired work is shed
  (:func:`record_shed`) instead of queued.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Callable, Optional

from ..core.log import logger
from ..obs import events as _events
from ..obs import metrics as _obs

log = logger("resilience")

#: ``Buffer.meta`` key carrying a :class:`Deadline` through the graph
DEADLINE_META_KEY = "deadline"
#: wire frame-meta key: REMAINING milliseconds at send time (a float) —
#: never an absolute stamp, so client and server clocks never mix
WIRE_KEY = "deadline_ms"

_reg = _obs.registry()
#: every shed is both a counter bump and a flight-recorder event; the
#: ``site`` label separates client-side drops from engine admission
_SHED_TOTAL = _reg.counter(
    "nnstpu_resilience_shed_total",
    "Work units dropped because their deadline had already expired",
    ("site",))
_RETRY_TOTAL = _reg.counter(
    "nnstpu_resilience_retries_total",
    "Retry attempts taken from a shared retry budget",
    ("site",))
_FALLBACK_TOTAL = _reg.counter(
    "nnstpu_resilience_fallback_total",
    "Buffers routed to a local fallback instead of the remote path",
    ("element",))
#: hedged sends are spent capacity, not free latency wins — account
#: every one so operators can see what the P95 tail costs
_HEDGE_TOTAL = _reg.counter(
    "nnstpu_resilience_hedges_total",
    "Hedged duplicate dispatches issued against slow primaries",
    ("element",))
#: 0=closed 1=half-open 2=open; sampled at collection time through a
#: weakref so the registry never pins a retired breaker
_BREAKER_STATE = _reg.gauge(
    "nnstpu_resilience_breaker_state",
    "Circuit state per breaker (0=closed, 1=half-open, 2=open)",
    ("breaker",))


# --------------------------------------------------------------------------- #
# Retry
# --------------------------------------------------------------------------- #

class RetryPolicy:
    """Exponential backoff with full jitter.

    ``delay(attempt)`` for attempt 0,1,2,… draws uniformly from
    ``[0, min(max_s, base_s * multiplier**attempt)]`` — the AWS
    "full jitter" scheme: the cap grows exponentially, the draw spreads
    retries of many clients across the whole window instead of
    synchronizing them into waves. Pass a seeded ``rng`` for
    deterministic schedules (tests, chaos runs); the default shares the
    module PRNG.
    """

    def __init__(self, base_s: float = 0.05, max_s: float = 1.0,
                 multiplier: float = 2.0, jitter: bool = True,
                 rng: Optional[random.Random] = None):
        if base_s <= 0 or max_s <= 0 or multiplier < 1.0:
            raise ValueError("base_s/max_s must be > 0, multiplier >= 1")
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.multiplier = float(multiplier)
        self.jitter = bool(jitter)
        self._rng = rng if rng is not None else random

    def cap(self, attempt: int) -> float:
        """The un-jittered backoff ceiling for ``attempt`` (0-based)."""
        return min(self.max_s, self.base_s * self.multiplier ** max(attempt, 0))

    def delay(self, attempt: int) -> float:
        c = self.cap(attempt)
        return self._rng.uniform(0.0, c) if self.jitter else c

    def sleep(self, attempt: int) -> float:
        """Sleep the jittered delay; returns the seconds slept."""
        d = self.delay(attempt)
        if d > 0:
            time.sleep(d)
        return d


class RetryBudget:
    """A single pool of attempts shared by every retry loop on one
    request path. Each loop calls :meth:`take` before an attempt; once
    the pool drains every loop sees False — nested loops can no longer
    multiply into attempts² total tries."""

    def __init__(self, attempts: int, site: str = "query"):
        self.attempts = max(int(attempts), 1)
        self.used = 0
        self._site = site

    def take(self) -> bool:
        """Consume one attempt; False once the budget is exhausted."""
        if self.used >= self.attempts:
            return False
        if self.used > 0:
            # the first try is free capacity, not a "retry"
            _RETRY_TOTAL.labels(self._site).inc()
        self.used += 1
        return True

    @property
    def remaining(self) -> int:
        return self.attempts - self.used

    @property
    def exhausted(self) -> bool:
        return self.used >= self.attempts


# --------------------------------------------------------------------------- #
# Circuit breaker
# --------------------------------------------------------------------------- #

#: breaker states (string-valued for snapshots; gauge codes below)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Closed → open → half-open → closed failure gate.

    CLOSED counts consecutive failures; at ``failure_threshold`` the
    circuit opens and :meth:`allow` refuses callers for ``reset_s``.
    After the cooldown the next :meth:`allow` transitions to HALF_OPEN
    and admits up to ``half_open_probes`` probe calls: one success
    closes the circuit, one failure re-opens it (restarting the
    cooldown). The ``clock`` is injectable so tests drive the full
    transition sequence without sleeping.

    Thread-safe; transitions emit ``resilience.breaker_open`` /
    ``breaker_half_open`` / ``breaker_close`` events and the state gauge
    samples live through a weakref.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_s: float = 5.0, half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1 or reset_s < 0 or half_open_probes < 1:
            raise ValueError("failure_threshold/half_open_probes must be "
                             ">= 1, reset_s >= 0")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_s = float(reset_s)
        self.half_open_probes = int(half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded-by: _lock
        self._failures = 0  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        self._probes = 0  # guarded-by: _lock
        ref = weakref.ref(self)
        _BREAKER_STATE.labels(name).set_function(
            lambda: (lambda b: 0 if b is None
                     else _STATE_CODE[b._state])(ref()))

    @property
    def state(self) -> str:
        with self._lock:
            # an elapsed cooldown is observable as half-open even before
            # the next allow() call lands
            if self._state == OPEN and \
                    self._clock() - self._opened_at >= self.reset_s:
                self._to_half_open()
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now? Open → False; half-open → True
        for the bounded probe quota only."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.reset_s:
                    return False
                self._to_half_open()
            if self._probes < self.half_open_probes:
                self._probes += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._to_closed()
            else:
                self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._to_open("probe failed")
                return
            self._failures += 1
            if self._state == CLOSED \
                    and self._failures >= self.failure_threshold:
                self._to_open(f"{self._failures} consecutive failures")

    # transitions run under self._lock (the event ring takes its own
    # independent lock; no ordering hazard)
    def _to_open(self, why: str) -> None:  # guarded-by: _lock
        self._state = OPEN
        self._opened_at = self._clock()
        self._probes = 0
        log.warning("breaker %s OPEN: %s", self.name, why)
        _events.record("resilience.breaker_open",
                       f"{self.name}: circuit opened ({why})",
                       severity="warning", breaker=self.name)

    def _to_half_open(self) -> None:  # guarded-by: _lock
        self._state = HALF_OPEN
        self._probes = 0
        _events.record("resilience.breaker_half_open",
                       f"{self.name}: cooldown elapsed, probing",
                       breaker=self.name)

    def _to_closed(self) -> None:  # guarded-by: _lock
        self._state = CLOSED
        self._failures = 0
        self._probes = 0
        log.info("breaker %s closed: probe succeeded", self.name)
        _events.record("resilience.breaker_close",
                       f"{self.name}: probe succeeded, circuit closed",
                       breaker=self.name)


# --------------------------------------------------------------------------- #
# Deadlines
# --------------------------------------------------------------------------- #

class Deadline:
    """A point in local monotonic time after which work is worthless.

    Created from a relative budget (:meth:`after_ms`); compared only
    against the local monotonic clock. Crossing the wire it is encoded
    as *remaining* milliseconds (:meth:`to_wire`) and re-anchored on the
    receiver's clock (:meth:`from_wire`) — transit time is absorbed into
    the budget rather than mis-credited by comparing two hosts' clocks.
    """

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)  # monotonic seconds

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + float(ms) / 1e3)

    @classmethod
    def after_s(cls, s: float) -> "Deadline":
        return cls(time.monotonic() + float(s))

    def remaining_s(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def to_wire(self) -> float:
        """Remaining budget in milliseconds (floored at 0)."""
        return max(self.remaining_s(), 0.0) * 1e3

    @classmethod
    def from_wire(cls, ms: Any) -> Optional["Deadline"]:
        try:
            return cls.after_ms(float(ms))
        except (TypeError, ValueError):
            return None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining_s() * 1e3:.1f}ms)"


def deadline_of(buf: Any) -> Optional[Deadline]:
    """The :class:`Deadline` riding on a buffer, if any."""
    d = buf.meta.get(DEADLINE_META_KEY)
    return d if isinstance(d, Deadline) else None


def set_deadline(buf: Any, deadline: Deadline) -> None:
    buf.meta[DEADLINE_META_KEY] = deadline


def record_shed(site: str, message: str, **attrs: Any) -> None:
    """Account one shed work unit: counter + ``resilience.shed`` event
    (one flag check each while obs is off)."""
    _SHED_TOTAL.labels(site).inc()
    _events.record("resilience.shed", message, severity="warning",
                   site=site, **attrs)


def record_fallback(element: str, message: str, **attrs: Any) -> None:
    """Account one buffer routed to a local fallback path."""
    _FALLBACK_TOTAL.labels(element).inc()
    _events.record("resilience.fallback", message, element=element, **attrs)


def record_hedge(element: str, message: str, **attrs: Any) -> None:
    """Account one hedged duplicate dispatch (query.router)."""
    _HEDGE_TOTAL.labels(element).inc()
    _events.record("resilience.hedge", message, element=element, **attrs)


def backend_breaker_name(owner: str, endpoint: str) -> str:
    """Canonical breaker name for one backend of a routed set —
    ``query:<owner>:<endpoint>`` — so the per-breaker state gauge
    separates backends instead of aggregating a fleet into one series.
    Cardinality is bounded by the configured backend set."""
    return f"query:{owner}:{endpoint}"


def fleet_breaker_name(controller: str) -> str:
    """Canonical breaker name for a fleet controller's scale actions —
    ``fleet:<controller>`` — a run of failed worker launches opens the
    breaker so the reconcile loop stops hammering a broken launch path
    instead of flapping. Cardinality: one per controller (usually 1)."""
    return f"fleet:{controller}"
