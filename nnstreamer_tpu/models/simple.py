"""Tiny built-in models used by tests and examples.

Mirrors the reference's custom test filters
(tests/nnstreamer_example/custom_example_{passthrough,scaler,average,...}) —
scaffolding models standing in for real networks — implemented as jax
functions registered in the zoo.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from ..core.types import TensorsInfo
from .zoo import ModelBundle, register_model


def _info_from(dims: str, types: str) -> TensorsInfo:
    return TensorsInfo.from_strings(dims, types)


def make_passthrough(dims: str = "3:224:224:1", types: str = "uint8", **_: Any) -> ModelBundle:
    info = _info_from(dims, types)
    return ModelBundle("passthrough", lambda *xs: xs if len(xs) > 1 else xs[0],
                       in_info=info, out_info=info)


def make_scaler(dims: str = "3:224:224:1", types: str = "float32",
                scale: str = "2.0", **_: Any) -> ModelBundle:
    info = _info_from(dims, types)
    s = float(scale)
    return ModelBundle("scaler", lambda x: x * s, in_info=info, out_info=info)


def make_average(dims: str = "3:224:224:1", types: str = "float32", **_: Any) -> ModelBundle:
    """Per-frame global average → one scalar per frame (custom_example_average)."""
    in_info = _info_from(dims, types)
    out_info = TensorsInfo.from_strings("1:1", types)
    return ModelBundle(
        "average",
        lambda x: jnp.mean(x.astype(jnp.float32), axis=tuple(range(1, x.ndim)),
                           keepdims=False).reshape(-1, 1).astype(x.dtype),
        in_info=in_info, out_info=out_info)


def make_matmul(n: str = "256", batch: str = "1", seed: str = "0", **_: Any) -> ModelBundle:
    """Dense layer stand-in: x @ W with a fixed random W (MXU exerciser)."""
    import jax

    dim, b = int(n), int(batch)
    key = jax.random.PRNGKey(int(seed))
    w = jax.random.normal(key, (dim, dim), jnp.float32) / np.sqrt(dim)
    info = TensorsInfo.from_strings(f"{dim}:{b}", "float32")
    return ModelBundle("matmul", lambda p, x: x @ p, params=w,
                       in_info=info, out_info=info)


register_model("passthrough", make_passthrough)
register_model("scaler", make_scaler)
register_model("average", make_average)
register_model("matmul", make_matmul)
