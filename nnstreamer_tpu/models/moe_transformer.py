"""MoE streaming transformer — expert-parallel long-sequence model family.

Beyond-reference capability (the reference has no large-model sharding;
its models are opaque single-device files, SURVEY §2.3): a streaming
transformer whose MLPs are switch-routed mixture-of-experts layers
(parallel/moe.py). Serving fans the expert stacks over an ``expert`` mesh
axis — dispatch/combine einsums become GSPMD all-to-alls over ICI — while
attention can still run sequence-parallel (parallel/ring.py), so BOTH the
context length and the parameter count scale with chips.

Zoo entry: ``zoo://moe_transformer?layers=2&dim=128&heads=8&experts=8``
(every second block is MoE, Switch-Transformer style). For mesh serving
use ``make_ep_infer(bundle, mesh)`` or wrap with ``parallel.sharded_bundle``
semantics via the returned jit.

Router metrics (load-balance loss, per-expert counts) are sown into the
``moe_metrics`` flax collection: training code applies with
``mutable=["moe_metrics"]`` to read them; plain serving ignores them.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.types import TensorsInfo
from ..parallel.moe import moe_apply
from .stream_transformer import Block
from .zoo import ModelBundle, register_model


class MoEBlock(Block):
    """Transformer block with a switch-MoE MLP. Shares Block's attention
    half; only the MLP vmethod differs."""

    n_experts: int = 8
    capacity_factor: float = 1.25

    def _mlp_residual(self, x):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype)(x)
        e, hidden = self.n_experts, d * self.mlp_ratio
        params = {
            "router": self.param(
                "router", nn.initializers.normal(1.0 / np.sqrt(d)),
                (d, e), jnp.float32),
            "w1": self.param(
                "w1", nn.initializers.normal(1.0 / np.sqrt(d)),
                (e, d, hidden), jnp.float32),
            "w2": self.param(
                "w2", nn.initializers.normal(1.0 / np.sqrt(hidden)),
                (e, hidden, d), jnp.float32),
        }
        cast = {key: val.astype(self.dtype) if key != "router" else val
                for key, val in params.items()}
        y, aux = moe_apply(cast, h.astype(self.dtype),
                           capacity_factor=self.capacity_factor)
        self.sow("moe_metrics", "load_balance_loss",
                 aux["load_balance_loss"])
        self.sow("moe_metrics", "expert_counts", aux["expert_counts"])
        return x + y.astype(self.dtype)


class MoEStreamTransformer(nn.Module):
    """Alternating dense/MoE blocks (Switch-style: odd blocks are MoE)."""

    layers: int = 2
    dim: int = 128
    heads: int = 8
    n_experts: int = 8
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        if x.shape[-1] != self.dim:
            x = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.layers):
            if i % 2 == 1:
                x = MoEBlock(self.dim, self.heads,
                             n_experts=self.n_experts,
                             capacity_factor=self.capacity_factor,
                             dtype=self.dtype,
                             attention_fn=self.attention_fn,
                             name=f"moe_block_{i}")(x)
            else:
                x = Block(self.dim, self.heads, dtype=self.dtype,
                          attention_fn=self.attention_fn,
                          name=f"block_{i}")(x)
        return nn.LayerNorm(dtype=self.dtype)(x).astype(jnp.float32)


def make_moe_transformer(layers: str = "2", dim: str = "128",
                         heads: str = "8", experts: str = "8",
                         seq: str = "256", in_dim: str = "",
                         batch: str = "1", seed: str = "0",
                         capacity_factor: str = "1.25",
                         dtype: str = "bfloat16", **_: Any) -> ModelBundle:
    L, D, B, E = int(seq), int(dim), int(batch), int(experts)
    d_in = int(in_dim) if in_dim else D
    model = MoEStreamTransformer(
        layers=int(layers), dim=D, heads=int(heads), n_experts=E,
        capacity_factor=float(capacity_factor),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    params = init_variables(model, int(seed),
                            jnp.zeros((B, L, d_in), jnp.float32))
    # drop the sown moe_metrics collection picked up during init: serving
    # never reads it, and it must not ride along into sharded placement
    params = {"params": params["params"]} if "params" in params else params
    return ModelBundle(
        "moe_transformer", lambda p, x: model.apply(p, x), params=params,
        in_info=TensorsInfo.from_strings(f"{d_in}:{L}:{B}", "float32"),
        out_info=TensorsInfo.from_strings(f"{D}:{L}:{B}", "float32"),
        metadata={"layers": int(layers), "dim": D, "heads": int(heads),
                  "experts": E, "seq": L,
                  "capacity_factor": float(capacity_factor),
                  "dtype": dtype})


def ep_param_shardings(params: Any, mesh, n_experts: int,
                       ep_axis: str = "expert") -> Any:
    """Sharding pytree for the param tree: expert weight stacks (leaves
    named w1/w2 under a moe block, leading dim == expert count) shard over
    ``ep_axis``; everything else replicates. Keyed on the param PATH, not
    shape alone, so an unrelated leaf that happens to have a matching
    leading dim is never expert-sharded."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        segs = [str(getattr(p, "key", p)) for p in path]
        shape = np.shape(leaf)
        is_expert_stack = (
            ep_axis in mesh.shape
            and segs and segs[-1] in ("w1", "w2")
            and any(s.startswith("moe") for s in segs)
            and shape and shape[0] == n_experts)
        out.append(NamedSharding(mesh, P(ep_axis) if is_expert_stack
                                 else P()))
    return jax.tree_util.tree_unflatten(treedef, out)


def _ep_x_sharding(mesh, dp_axis):
    """Input placement for ep inference: batch over dp_axis when it has
    width, else replicated. The ONE spot both the jit compilation and the
    filter-side placement derive from."""
    dp = mesh.shape.get(dp_axis, 1) if dp_axis else 1
    return dp, NamedSharding(mesh, P(dp_axis) if dp > 1 else P())


def make_ep_infer(bundle: ModelBundle, mesh, ep_axis: str = "expert",
                  dp_axis: str = "data"):
    """(infer_fn, placed_params) with expert stacks sharded over
    ``ep_axis`` and the token batch over ``dp_axis`` (when present)."""
    n_experts = bundle.metadata["experts"]
    shardings = ep_param_shardings(bundle.params, mesh, n_experts, ep_axis)
    placed = jax.tree_util.tree_map(jax.device_put, bundle.params, shardings)
    dp, x_sharding = _ep_x_sharding(mesh, dp_axis)
    x_spec = x_sharding.spec
    apply = bundle.apply
    jitted = jax.jit(
        lambda p, x: apply(p, x),
        in_shardings=(shardings, NamedSharding(mesh, x_spec)),
        out_shardings=NamedSharding(mesh, x_spec))
    from ..parallel.moe import dp_guard

    return dp_guard(jitted, dp, dp_axis, what="ep infer"), placed


def make_sp_ep_infer(bundle: ModelBundle, mesh, sp_axis: str = "sp",
                     ep_axis: str = "expert", sp_mode: str = "ring"):
    """(infer_fn, placed_params) composing BOTH long-context and expert
    scaling on one 2D mesh: attention runs sequence-parallel over
    ``sp_axis`` (ring ppermute or Ulysses all-to-all — context length
    scales with that axis) while MoE expert stacks shard over ``ep_axis``
    (parameter count scales with that axis). Inputs/outputs are
    globally-shaped with the L axis sharded over ``sp_axis``."""
    from ..parallel.ring import sp_attention_fn

    meta = bundle.metadata
    attn = sp_attention_fn(sp_mode, mesh, sp_axis)
    model = MoEStreamTransformer(
        layers=meta["layers"], dim=meta["dim"], heads=meta["heads"],
        n_experts=meta["experts"],
        capacity_factor=meta.get("capacity_factor", 1.25),
        dtype=jnp.bfloat16 if meta.get("dtype") == "bfloat16"
        else jnp.float32,
        attention_fn=attn)
    shardings = ep_param_shardings(bundle.params, mesh, meta["experts"],
                                   ep_axis)
    placed = jax.tree_util.tree_map(jax.device_put, bundle.params, shardings)
    x_spec = P(None, sp_axis, None)
    jitted = jax.jit(
        lambda p, x: model.apply(p, x),
        in_shardings=(shardings, NamedSharding(mesh, x_spec)),
        out_shardings=NamedSharding(mesh, x_spec))

    def infer(p, x):
        sp = mesh.shape[sp_axis]
        if x.shape[1] % sp:
            raise ValueError(
                f"sp×ep infer: sequence {x.shape[1]} not divisible by the "
                f"{sp_axis!r} axis size {sp}")
        return jitted(p, x)

    return infer, placed


def ep_bundle(bundle: ModelBundle, mesh, ep_axis: str = "expert",
              dp_axis: str = "data") -> ModelBundle:
    """Wrap for pipeline serving: ``tensor_filter model=ep_bundle(b, mesh)``
    fans each request over the mesh with expert weights sharded — the MoE
    analog of parallel.sharded_bundle (pod-slice offload). Carries
    ``jit: False`` (already a pjit program) and the input sharding the
    filter places incoming host tensors with."""
    infer, placed = make_ep_infer(bundle, mesh, ep_axis, dp_axis)
    _, x_sharding = _ep_x_sharding(mesh, dp_axis)
    # drop private "_"-keys: an inherited _w8_bundle/_jit_cache would let
    # a later quant/compile cache-hit bypass the mesh program entirely
    public_meta = {k: v for k, v in bundle.metadata.items()
                   if not k.startswith("_")}
    return ModelBundle(
        f"{bundle.name}@ep{mesh.shape.get(ep_axis, 1)}",
        lambda x: infer(placed, x),
        in_info=bundle.in_info, out_info=bundle.out_info,
        metadata={**public_meta, "jit": False,
                  "input_sharding": x_sharding})


register_model("moe_transformer", make_moe_transformer)
