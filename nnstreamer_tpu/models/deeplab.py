"""DeepLab-v3 semantic segmentation — BASELINE config 3.

Native flax stand-in for the reference's deeplabv3_257 tflite
(tests/test_models/models/deeplabv3_257_mv_gpu.tflite + image_segment
decoder scheme tflite-deeplab): MobileNet-v2 backbone (output stride 16)
+ ASPP (atrous pyramid) + bilinear upsample to input size → per-pixel class
logits [classes:W:H:1], exactly what tensordec-imagesegment.c argmaxes.
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .mobilenet_v2 import ConvBNReLU, InvertedResidual, _make_divisible, preprocess_uint8
from .zoo import ModelBundle, register_model


class ASPP(nn.Module):
    features: int = 256
    rates: tuple = (6, 12, 18)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        branches: List[jax.Array] = [
            ConvBNReLU(self.features, kernel=1, dtype=self.dtype)(x, train)]
        for r in self.rates:
            y = nn.Conv(self.features, (3, 3), padding="SAME",
                        kernel_dilation=(r, r), use_bias=False,
                        dtype=self.dtype)(x)
            y = nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(y)
            branches.append(nn.relu(y))
        # image-level pooling branch
        g = jnp.mean(x, axis=(1, 2), keepdims=True)
        g = ConvBNReLU(self.features, kernel=1, dtype=self.dtype)(g, train)
        g = jnp.broadcast_to(g, branches[0].shape)
        branches.append(g)
        y = jnp.concatenate(branches, axis=-1)
        return ConvBNReLU(self.features, kernel=1, dtype=self.dtype)(y, train)


class DeepLabV3(nn.Module):
    num_classes: int = 21
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        x = x.astype(self.dtype)
        w = self.width
        x = ConvBNReLU(_make_divisible(32 * w), stride=2, dtype=self.dtype)(x, train)
        # output-stride 16: last stride-2 stage dilated instead of strided
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 1), (6, 320, 1, 1)]
        for t, c, n, s in cfg:
            for i in range(n):
                x = InvertedResidual(_make_divisible(c * w), s if i == 0 else 1,
                                     t, dtype=self.dtype)(x, train)
        x = ASPP(dtype=self.dtype)(x, train)
        x = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(x)
        x = jax.image.resize(x.astype(jnp.float32),
                             (x.shape[0], size[0], size[1], self.num_classes),
                             method="bilinear")
        return x


def make_deeplab_v3(width: str = "1.0", size: str = "257",
                    num_classes: str = "21", seed: str = "0",
                    batch: str = "1", dtype: str = "bfloat16",
                    **_: Any) -> ModelBundle:
    w, hw, nc, b = float(width), int(size), int(num_classes), int(batch)
    model = DeepLabV3(num_classes=nc, width=w,
                      dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    variables = init_variables(model, int(seed),
                               jnp.zeros((b, hw, hw, 3), jnp.float32))

    def apply(params, x):
        if x.dtype == jnp.uint8:
            x = preprocess_uint8(x)
        return model.apply(params, x, train=False)

    return ModelBundle(
        "deeplab_v3", apply, params=variables,
        in_info=TensorsInfo.from_strings(f"3:{hw}:{hw}:{b}", "uint8"),
        out_info=TensorsInfo.from_strings(f"{nc}:{hw}:{hw}:{b}", "float32"),
        preprocess=preprocess_uint8,
        metadata={"size": hw, "classes": nc})


register_model("deeplab_v3", make_deeplab_v3)
