"""Streaming LSTM cell — the tensor_repo loop workload (BASELINE config 5).

Reference analog: tests/nnstreamer_example/custom_example_LSTM (a C LSTM
cell custom filter driven through a tensor_repo cycle). Here: a flax
LSTMCell exposed as a multi-input/multi-output ModelBundle
``(x, h, c) -> (y, h', c')`` so the repo-loop pipeline carries recurrent
state as ordinary stream tensors.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .zoo import ModelBundle, register_model


def make_lstm_cell(features: str = "32", input_size: str = "32",
                   batch: str = "1", seed: str = "0", **_: Any) -> ModelBundle:
    f, inp, b = int(features), int(input_size), int(batch)
    cell = nn.LSTMCell(features=f)
    key = jax.random.PRNGKey(int(seed))
    dummy_x = jnp.zeros((b, inp), jnp.float32)
    carry0 = cell.initialize_carry(key, dummy_x.shape)
    from .zoo import init_variables

    params = init_variables(cell, int(seed), carry0, dummy_x)

    def apply(p, x, h, c):
        (new_c, new_h), y = cell.apply(p, (c, h), x)
        return y, new_h, new_c

    io = TensorsInfo.from_strings(
        f"{inp}:{b},{f}:{b},{f}:{b}", "float32,float32,float32")
    out = TensorsInfo.from_strings(
        f"{f}:{b},{f}:{b},{f}:{b}", "float32,float32,float32")
    return ModelBundle("lstm_cell", apply, params=params,
                       in_info=io, out_info=out,
                       metadata={"features": f, "input": inp})


register_model("lstm_cell", make_lstm_cell)
