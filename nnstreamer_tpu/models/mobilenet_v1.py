"""MobileNet-v1 in flax — the reference's flagship test model.

The reference's golden pipelines serve mobilenet_v1 (quantized tflite:
tests/test_models/models/mobilenet_v1_1.0_224_quant.tflite, SSAT label
goldens in tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:69-76).
This is the native flax v1 (Howard et al. 2017: a stem conv then 13
depthwise-separable blocks), NHWC for the MXU, bf16 compute;
``custom="quant=w8"`` at the filter mirrors the quantized-tflite serving
shape (int8 weights, dequant fused).

Reuses ConvBNReLU and the tflite uint8 preprocessing convention from
mobilenet_v2.py; output is 1001-way logits (background + ImageNet).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .mobilenet_v2 import ConvBNReLU, _make_divisible
from .zoo import ModelBundle, register_model

#: (out channels, stride) per depthwise-separable block — v1 paper table 1
_BLOCKS: Sequence[Tuple[int, int]] = (
    (64, 1),
    (128, 2), (128, 1),
    (256, 2), (256, 1),
    (512, 2), (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
    (1024, 2), (1024, 1),
)


class DepthwiseSeparable(nn.Module):
    features: int
    stride: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch = x.shape[-1]
        x = ConvBNReLU(ch, kernel=3, stride=self.stride, groups=ch,
                       dtype=self.dtype)(x, train)       # depthwise
        return ConvBNReLU(self.features, kernel=1,
                          dtype=self.dtype)(x, train)    # pointwise


class MobileNetV1(nn.Module):
    num_classes: int = 1001
    width: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = ConvBNReLU(_make_divisible(32 * self.width), stride=2,
                       dtype=self.dtype)(x, train)
        for c, s in _BLOCKS:
            x = DepthwiseSeparable(_make_divisible(c * self.width),
                                   stride=s, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def make_mobilenet_v1(**options: Any) -> ModelBundle:
    from .mobilenet_v2 import make_mobilenet_bundle

    return make_mobilenet_bundle("mobilenet_v1", MobileNetV1, **options)


register_model("mobilenet_v1", make_mobilenet_v1)
