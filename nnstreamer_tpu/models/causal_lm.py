"""Causal transformer LM with a streaming KV-cache decode step.

Long-context streaming as a *pipeline loop* (the tensor_repo recurrence
the reference uses for its LSTM example, tests/nnstreamer_repo_lstm):
the KV cache is carried as ordinary device-resident stream tensors, so
autoregressive decoding is

    tokens ─┐
            ├─ tensor_mux ! tensor_filter(zoo://causal_lm?...) ! demux
    state ──┘        ▲                                        │
  (reposrc)          └── logits → sink;  (k,v,pos) → reposink ┘

One token per loop iteration, O(1) work per step against an O(max_len)
cache — no recompute of the prefix. Shapes are static (cache is
pre-allocated at ``max_len``; ``pos`` masks the unwritten tail) so XLA
compiles the step exactly once.

Exactness contract: step-decoding a sequence token-by-token produces the
same logits as the full causal forward pass (``lm_forward``) at every
position (tests/test_causal_lm.py).

Cache transport layout: rank-3 ``(layers·batch·heads, max_len, head_dim)``
so it rides the tensor type system's rank limit; the step reshapes to the
logical 5-D layout internally.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import TensorsInfo
from ..ops.int8 import matmul_any as _mm
from ..ops.int8 import mlp_matmul as _mlp
from ..ops.int8 import quantize_weight, stack_shape
from .zoo import ModelBundle, register_model


def init_causal_lm(rng: jax.Array, vocab: int, d_model: int, n_heads: int,
                   n_layers: int, max_len: int,
                   d_ff: int = 0) -> Dict[str, jax.Array]:
    d_ff = d_ff or 4 * d_model
    ks = jax.random.split(rng, 6)
    s = 1.0 / math.sqrt(d_model)
    sf = 1.0 / math.sqrt(d_ff)
    L = n_layers
    return {
        "embed": jax.random.normal(ks[0], (vocab, d_model)) * 0.02,
        "pos_embed": jax.random.normal(ks[1], (max_len, d_model)) * 0.02,
        "wqkv": jax.random.normal(ks[2], (L, d_model, 3 * d_model)) * s,
        "wo": jax.random.normal(ks[3], (L, d_model, d_model)) * s,
        "w1": jax.random.normal(ks[4], (L, d_model, d_ff)) * s,
        "w2": jax.random.normal(ks[5], (L, d_ff, d_model)) * sf,
        "ln1": jnp.ones((L, d_model)),
        "ln2": jnp.ones((L, d_model)),
        "lnf": jnp.ones((d_model,)),
    }


def quantize_lm_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """w8a8 serving form of an LM param tree: the four GEMM stacks
    (wqkv/wo/w1/w2) become int8 payloads + per-output-channel scales
    (ops/int8.quantize_weight); embeddings and norms stay float. Every
    execution form — forward, prefill (dense/flash/ring), decode step,
    verify window, vmapped slots — consumes the quantized tree through
    the same ``matmul_any`` sites, so this one transform turns the whole
    family int8 with no flag-threading; the scanned layer stacks slice
    into per-layer quantized dicts transparently. TPU v5e runs the int8
    contractions at 2x the bf16 peak (docs/performance.md roofline).
    Composes with the TP mesh: `parallel/tp_decode.tp_shard_params`
    relayouts a quantized tree preserving the single-device grids, so
    distributed int8 decode matches this path token-for-token."""
    qp = dict(params)
    for k in ("wqkv", "wo", "w1", "w2"):
        qp[k] = quantize_weight(params[k])
    return qp


def _ln(x, scale):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * scale


def _split_heads(t, n_heads):
    b, l, d = t.shape
    return t.reshape(b, l, n_heads, d // n_heads).transpose(0, 2, 1, 3)


#: TPU matmuls default to bf16 accumulation, which makes prefill vs
#: step-decode logits drift ~1e-3 (different contraction orders). This
#: family's contract is exactness between its execution forms, so its
#: matmuls pin float32 precision (measured 6e-8 agreement on v5e).
#: Large production models would keep bf16 and accept the drift.
_PRECISION = "float32"


def lm_forward(params: Dict[str, jax.Array], tokens: jax.Array,
               n_heads: int) -> jax.Array:
    """Full causal forward (the oracle): (B, T) int32 → (B, T, vocab)."""
    with jax.default_matmul_precision(_PRECISION):
        return _lm_forward(params, tokens, n_heads)


def _block_body(h, layer, mask, n_heads, attention_fn=None):
    """One transformer block over a full (masked) sequence; returns the
    new hidden state plus this layer's per-head K/V (for cache prefill).
    The ONE definition all full-sequence execution forms share.
    ``attention_fn`` (q,k,v)->o replaces the dense causal attention
    (e.g. sequence-parallel ring attention — it must apply causality
    itself)."""
    wqkv, wo, w1, w2, ln1, ln2 = layer
    a = _ln(h, ln1)
    q, k, v = jnp.split(_mm(a, wqkv), 3, axis=-1)
    qh, kh, vh = (_split_heads(z, n_heads) for z in (q, k, v))
    if attention_fn is not None:
        o = attention_fn(qh, kh, vh)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(qh.shape[-1])
        s = jnp.where(mask, s, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vh)
    o = o.transpose(0, 2, 1, 3).reshape(h.shape)
    h = h + _mm(o, wo)
    m = _ln(h, ln2)
    return h + _mlp(m, w1, w2), kh, vh


def _layer_stack(params):
    return (params["wqkv"], params["wo"], params["w1"], params["w2"],
            params["ln1"], params["ln2"])


def _lm_forward(params, tokens, n_heads):
    b, t = tokens.shape
    x = params["embed"][tokens] + params["pos_embed"][:t][None]
    mask = jnp.tril(jnp.ones((t, t), bool))

    def block(h, layer):
        h, _, _ = _block_body(h, layer, mask, n_heads)
        return h, None

    x, _ = jax.lax.scan(block, x, _layer_stack(params))
    return _ln(x, params["lnf"]) @ params["embed"].T


def lm_prefill(params: Dict[str, jax.Array], tokens: jax.Array,
               n_heads: int, max_len: int, mesh=None,
               sp_axis: str = "sp", flash: "bool | None" = None
               ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Process a whole prompt in ONE forward and emit the populated cache.

    tokens: (B, T) int32 with T <= max_len. Returns (logits_last (B, vocab),
    kcache, vcache, pos=T) in the flat transport layout — decode then
    continues token-by-token via ``lm_decode_step``. This is the standard
    prefill/decode split: prompt cost is one big (MXU-friendly) forward,
    not T sequential steps.

    With ``mesh``, prompt attention runs **sequence-parallel** over
    ``mesh[sp_axis]`` via causal ring attention (parallel/ring.py):
    prompt length scales with the axis size (T must divide by it) while
    the emitted cache and subsequent decode are unchanged — long-context
    prefill across chips, streaming decode after.

    ``flash=True`` (single-device) swaps the dense attention for the
    blockwise pallas kernel — no (T, T) score matrix in HBM. Defaults to
    the ``NNS_LM_FLASH=1`` env var; either way the choice resolves at
    TRACE time and is baked into a jitted prefill's cached executable.
    """
    with jax.default_matmul_precision(_PRECISION):
        return _lm_prefill(params, tokens, n_heads, max_len, mesh, sp_axis,
                           flash)


def _lm_prefill(params, tokens, n_heads, max_len, mesh=None, sp_axis="sp",
                flash=None, true_len=None):
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(
            f"lm_prefill: prompt length {t} exceeds max_len={max_len}")
    if true_len is not None and (mesh is not None or flash):
        raise ValueError(
            "lm_prefill: true_len= (padded-prompt masking) is a "
            "dense-attention feature; the ring/flash paths apply "
            "causality internally and cannot see it")
    if true_len is not None and not isinstance(true_len, jax.core.Tracer):
        # eager mirror of tp_prefill's check — only when the value is
        # concrete (under jit it is a tracer and the caller's eager
        # entry point has already validated it)
        tl_v = int(true_len)
        if not 1 <= tl_v <= t:
            raise ValueError(
                f"lm_prefill: true_len={tl_v} outside [1, {t}] "
                "(padded prompt length)")
    n_layers = stack_shape(params["wqkv"])[0]
    d_model = params["embed"].shape[1]
    hd = d_model // n_heads
    x = params["embed"][tokens] + params["pos_embed"][:t][None]
    pad = [(0, 0), (0, 0), (0, max_len - t), (0, 0)]
    attn = mask = None
    if mesh is not None:
        from ..parallel.ring import sp_attention_fn

        if sp_axis not in mesh.shape:
            raise ValueError(
                f"lm_prefill: mesh has no {sp_axis!r} axis "
                f"(axes: {dict(mesh.shape)})")
        if t % mesh.shape[sp_axis]:
            raise ValueError(
                f"lm_prefill: prompt length {t} not divisible by the "
                f"{sp_axis!r} axis size {mesh.shape[sp_axis]}")
        if flash:
            raise ValueError(
                "lm_prefill: flash=True conflicts with mesh= (the sp path "
                "uses ring attention; run flash single-device)")
        # NNS_LM_SP_MODE=ring-flash composes the pallas kernel inside the
        # ring steps (long-context memory profile); default plain ring
        attn = sp_attention_fn(os.environ.get("NNS_LM_SP_MODE", "ring"),
                               mesh, sp_axis, causal=True)
    elif true_len is None and (
            flash if flash is not None
            else os.environ.get("NNS_LM_FLASH", "") == "1"):
        # (true_len forces the dense branch even under NNS_LM_FLASH=1:
        # the kernel applies causality internally and cannot column-mask
        # a padded prompt — explicit flash=True raised above)
        # single-device flash path: blockwise pallas kernel, no (t, t)
        # score matrix in HBM (ops/pallas/flash_attention.py). NOTE: both
        # the explicit flag and the env var resolve at TRACE time — a
        # jitted prefill bakes the choice into the cached executable
        from ..ops.pallas.flash_attention import flash_attention

        attn = lambda qh, kh, vh: flash_attention(  # noqa: E731
            qh, kh, vh, causal=True)
    else:
        # only the dense path needs the O(t²) mask; the sp path exists
        # precisely to avoid materializing it on one device
        mask = jnp.tril(jnp.ones((t, t), bool))
        if true_len is not None:
            # right-padded prompt: padded columns can never be attended
            tl = jnp.asarray(true_len).reshape(()).astype(jnp.int32)
            mask = mask & (jnp.arange(t) < tl)[None, :]

    def block(h, layer):
        h, kh, vh = _block_body(h, layer, mask, n_heads, attn)
        return h, (jnp.pad(kh, pad), jnp.pad(vh, pad))

    x, (kc, vc) = jax.lax.scan(block, x, _layer_stack(params))
    if true_len is None:
        last = x[:, -1:]
        pos = jnp.full((1,), t, jnp.int32)
    else:
        last = jax.lax.dynamic_index_in_dim(x, tl - 1, axis=1,
                                            keepdims=True)
        pos = tl.reshape(1)
    logits = (_ln(last, params["lnf"]) @ params["embed"].T)[:, 0]
    flat = (n_layers * b * n_heads, max_len, hd)
    return logits, kc.reshape(flat), vc.reshape(flat), pos


def lm_decode_step(params: Dict[str, jax.Array], token: jax.Array,
                   kcache: jax.Array, vcache: jax.Array, pos: jax.Array,
                   n_heads: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One streaming decode step.

    token: (B, 1) int32; kcache/vcache: (L·B·H, max_len, hd) flat transport
    layout; pos: (1,) int32 — next write position. Returns
    (logits (B, vocab), kcache', vcache', pos+1).

    Cache-capacity contract: callers must stop at ``pos == max_len``
    (prompt + generated tokens ≤ the cache's max_len). Decoding past
    capacity cannot raise from inside the compiled program (pos is a
    traced value), so the step NaN-poisons the logits instead —
    ``dynamic_update_slice`` would otherwise clamp the write onto the
    last slot and return silently wrong results.
    """
    with jax.default_matmul_precision(_PRECISION):
        return _lm_decode_step(params, token, kcache, vcache, pos, n_heads)


def _lm_decode_step(params, token, kcache, vcache, pos, n_heads):
    # exactly the W=1 case of the verify window (one shared body — the
    # cache-write/masking/poison contracts live in one place)
    logits, kc, vc, pos = _lm_verify_window(
        params, token, kcache, vcache, pos, n_heads)
    return logits[:, 0], kc, vc, pos


def lm_verify_window(params: Dict[str, jax.Array], tokens: jax.Array,
                     kcache: jax.Array, vcache: jax.Array, pos: jax.Array,
                     n_heads: int
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Speculative-verify step: consume a WINDOW of W tokens at cache
    positions pos..pos+W-1 and return logits at EVERY window position.

    The device half of speculative decoding: the caller feeds
    ``[carried_token, draft_1..draft_{W-1}]``, gets back the model's
    next-token distribution after each of them in ONE dispatch, and
    accepts the longest prefix where the draft matches the model
    (`serving/lm_engine.py` speculative mode). Within the window, query
    row j attends cache columns <= pos+j — K/V for the whole window are
    written first, and rows never see later columns, so row j's logits
    equal a sequential decode step that consumed tokens[:, :j+1] up to
    matmul associativity (~1e-7 at f32: the W-row matmul contracts in a
    different order) with identical argmax except at ties below that
    scale — greedy acceptance reproduces sequential greedy decode
    (tests/test_lm_spec.py pins both levels).
    Rejected-draft K/V slots beyond the accepted count become garbage,
    but a later step at position p attends col <= p only after
    overwriting slot p — the same overwrite-before-visible invariant
    bucketed prefill relies on (lm_prefill_masked), so the caller
    "rolls back" by just setting pos lower.

    tokens: (B, W) int32; caches in the flat transport layout; pos:
    (1,) int32. Returns (logits (B, W, vocab), kcache', vcache',
    pos+W). Windows past capacity (pos+W > max_len) NaN-poison the
    logits, mirroring lm_decode_step's contract.
    """
    with jax.default_matmul_precision(_PRECISION):
        return _lm_verify_window(
            params, tokens, kcache, vcache, pos, n_heads)


def _lm_verify_window(params, tokens, kcache, vcache, pos, n_heads):
    n_layers = stack_shape(params["wqkv"])[0]
    b, w = tokens.shape
    d_model = params["embed"].shape[1]
    hd = d_model // n_heads
    max_len = kcache.shape[-2]
    p = jnp.asarray(pos).reshape(())

    kc = kcache.reshape(n_layers, b, n_heads, max_len, hd)
    vc = vcache.reshape(n_layers, b, n_heads, max_len, hd)
    x = params["embed"][tokens] + \
        jax.lax.dynamic_slice_in_dim(params["pos_embed"], p, w)[None]
    # row j sees columns <= p+j (its own slot included, later rows' not)
    live = (jnp.arange(max_len)[None, :] <=
            (p + jnp.arange(w))[:, None])[None, None]   # (1,1,W,max_len)

    def block(carry, layer):
        # the cache rides the CARRY, not the scan ys: a ys-threaded cache
        # makes XLA rewrite all L·B·H·max_len slots every token, while a
        # carried buffer takes in-place dynamic_update_slice writes of
        # just the new (B, H, W, hd) slots per layer — the difference is
        # ~half the per-step HBM traffic at serving shapes
        h, kc, vc = carry
        wqkv, wo, w1, w2, ln1, ln2, li = layer
        a = _ln(h, ln1)
        q, k, v = jnp.split(_mm(a, wqkv), 3, axis=-1)      # (B, W, D)
        q = _split_heads(q, n_heads)                       # (B, H, W, hd)
        k = _split_heads(k, n_heads)[None].astype(kc.dtype)
        v = _split_heads(v, n_heads)[None].astype(vc.dtype)
        kc = jax.lax.dynamic_update_slice(kc, k, (li, 0, 0, p, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (li, 0, 0, p, 0))
        kc_l = jax.lax.dynamic_index_in_dim(kc, li, 0, keepdims=False)
        vc_l = jax.lax.dynamic_index_in_dim(vc, li, 0, keepdims=False)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kc_l) / math.sqrt(hd)
        s = jnp.where(live, s, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vc_l)
        o = o.transpose(0, 2, 1, 3).reshape(h.shape)
        h = h + _mm(o, wo)
        m = _ln(h, ln2)
        return (h + _mlp(m, w1, w2), kc, vc), None

    (x, kc, vc), _ = jax.lax.scan(
        block, (x, kc, vc),
        (params["wqkv"], params["wo"], params["w1"],
         params["w2"], params["ln1"], params["ln2"],
         jnp.arange(n_layers, dtype=jnp.int32)),
        # full unroll: step ops are tiny (B·W rows), so the win is XLA
        # prefetching the next layer's weights while this one runs;
        # n_layers is small and static, compile cost is bounded
        unroll=True)
    logits = _ln(x, params["lnf"]) @ params["embed"].T   # (B, W, vocab)
    # cache overflow (window past capacity) surfaces as NaN logits, not
    # as a silent clamped overwrite of the last slots — lm_decode_step doc
    logits = jnp.where(p + w > max_len, jnp.nan, logits)
    flat = (n_layers * b * n_heads, max_len, hd)
    return (logits, kc.reshape(flat), vc.reshape(flat),
            (p + w).reshape(1).astype(jnp.int32))


def lm_verify_window_slots(params: Dict[str, jax.Array], tokens: jax.Array,
                           kcaches: jax.Array, vcaches: jax.Array,
                           poss: jax.Array, n_heads: int
                           ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                      jax.Array]:
    """Verify windows for S independent streams at per-slot positions
    (``jax.vmap`` of :func:`lm_verify_window`, the same construction as
    lm_decode_step_slots). tokens: (S, W); caches with a leading slot
    axis; poss: (S, 1). Returns (logits (S, W, vocab), caches',
    poss+W)."""
    with jax.default_matmul_precision(_PRECISION):
        step = lambda tok, kc, vc, pos: _lm_verify_window(  # noqa: E731
            params, tok[None], kc, vc, pos, n_heads)
        logits, kc, vc, pos = jax.vmap(step)(
            tokens, kcaches, vcaches, poss)
        return logits[:, 0], kc, vc, pos


def lm_prefill_masked(params: Dict[str, jax.Array], tokens: jax.Array,
                      true_len: jax.Array, n_heads: int, max_len: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Prefill a right-PADDED prompt exactly: ``tokens`` is (1, Tb) with
    the real prompt in the first ``true_len`` positions (traced scalar).

    Serving engines bucket prompt lengths (pad Tb up to a few fixed
    sizes) so admission costs one compile per BUCKET, not per distinct
    prompt length. Exactness relies on two masks: attention columns are
    limited to ``col < true_len`` (padded rows can't leak in), and the
    returned last-token logits come from row ``true_len - 1``. K/V
    written at positions >= true_len ARE garbage, but a decode step at
    position p attends only ``col <= p`` after overwriting slot p, so a
    garbage slot is always overwritten before it becomes visible
    (`serving/lm_engine.py` relies on this).

    Returns (logits (1, vocab), kcache, vcache, pos=true_len) in the
    same flat transport layout as ``lm_prefill`` — it IS ``_lm_prefill``
    (one shared body) with the extra column mask and last-row selection.
    """
    with jax.default_matmul_precision(_PRECISION):
        return _lm_prefill(params, tokens, n_heads, max_len,
                           true_len=true_len)


def lm_decode_step_slots(params: Dict[str, jax.Array], tokens: jax.Array,
                         kcaches: jax.Array, vcaches: jax.Array,
                         poss: jax.Array, n_heads: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """One decode step for S INDEPENDENT streams at per-slot positions.

    The continuous-batching primitive: ``jax.vmap`` of the single-stream
    ``lm_decode_step`` over a leading slot axis, so each slot carries its
    own cache, write position, and liveness mask while the matmuls batch
    onto the MXU. Per-slot cache writes lower to one batched scatter.
    Exactness with the single-stream path is by construction (same
    program under vmap; tests/test_lm_serving.py pins it).

    tokens: (S, 1, 1) int32; kcaches/vcaches: (S, layers·heads, max_len,
    head_dim); poss: (S, 1) int32. Returns (logits (S, 1, vocab),
    kcaches', vcaches', poss+1). Slots past capacity NaN-poison their own
    row only. Exactly the W=1 case of :func:`lm_verify_window_slots`
    (one shared vmap wrapper; only the token layout differs).
    """
    return lm_verify_window_slots(
        params, tokens[:, :, 0], kcaches, vcaches, poss, n_heads)


# --------------------------------------------------------------------------- #
# Paged KV cache execution forms (serving/kv_cache.py page pools)
#
# The paged kernels do NOT reimplement attention. Each step GATHERS a
# slot's pages into the exact flat per-slot cache layout the contiguous
# kernels consume, runs the ONE shared `_lm_verify_window` body, and
# SCATTERS back only the pages the step could have touched. Exactness
# paged-vs-contiguous is therefore by construction, not by a parallel
# implementation (tests/test_kv_paging.py pins it bit-for-bit).
#
# Static-shape discipline: `page_size` and the table width B (the
# pages-per-slot bound — a slot's view is B·page_size tokens, its
# effective max_len) are baked into the executable, so paging adds no
# new compile axis beyond the buckets the engine already has. The
# gathered view is a transient of S·B·page_size tokens — the engine
# sizes B to the slot-equivalent budget, which is what keeps "hundreds
# of queued requests" from meaning "hundreds of resident caches".
# --------------------------------------------------------------------------- #


def _paged_view(pool, table):
    """Gather one slot's pages into a contiguous flat cache view.

    pool: (n_pages+1, L·H, ps, hd); table: (B,) int32 page ids. Returns
    (L·H, B·ps, hd) — exactly the single-slot transport layout with
    max_len = B·ps, so `_lm_verify_window` runs on it unchanged (it
    reads capacity from the cache shape). Table rows past the request's
    allocation hold the null page (id 0): their zeros are garbage the
    causal `live` mask never attends.
    """
    pages = pool[table]                              # (B, LH, ps, hd)
    b, lh, ps, hd = pages.shape
    return pages.transpose(1, 0, 2, 3).reshape(lh, b * ps, hd)


#: (pool, tables (S, B)) -> (S, L·H, B·ps, hd) — one batched gather
paged_view_slots = jax.vmap(_paged_view, in_axes=(None, 0))


def paged_touch_span(w: int, page_size: int, n_tables: int) -> int:
    """Pages a W-token window can touch at worst alignment (start at a
    page's last token): (w-1)//ps + 2, capped at the table width. Static
    — the scatter width is part of the executable, not data."""
    return min(n_tables, (w - 1) // page_size + 2)


def _writeback_window(view, table, p0, nt):
    """Slice the ``nt`` pages around write position ``p0`` out of a
    modified view. Returns (ids (nt,), pages (nt, L·H, ps, hd)). The
    start is left-clipped so the window stays inside the table; clipped
    windows re-write earlier pages with the unchanged bits they were
    gathered with — harmless, and it keeps ``nt`` static."""
    lh, m, hd = view.shape
    b = table.shape[0]
    ps = m // b
    pages = view.reshape(lh, b, ps, hd).transpose(1, 0, 2, 3)
    start = jnp.clip(jnp.asarray(p0).reshape(()) // ps, 0, b - nt)
    ids = jax.lax.dynamic_slice_in_dim(table, start, nt)
    win = jax.lax.dynamic_slice_in_dim(pages, start, nt, axis=0)
    return ids, win


def _paged_update(pool, view, table, p0, nt):
    """Scatter one slot's touched pages back into the pool."""
    ids, win = _writeback_window(view, table, p0, nt)
    return pool.at[ids].set(win)


def paged_update_slots(pool, views, tables, p0s, nt: int):
    """Scatter S slots' touched pages back in ONE pool write.

    Duplicate scatter indices are safe by the allocator's invariants:
    modified positions live in exclusively-owned pages (COW discipline),
    shared pages in a clipped window carry their unchanged gathered
    bits, and empty slots' zeroed tables collide only on the null page
    (never read). So last-writer-wins ambiguity never changes bits that
    anyone attends.
    """
    ids, wins = jax.vmap(
        lambda v, t, p: _writeback_window(v, t, p, nt))(views, tables, p0s)
    return pool.at[ids.reshape(-1)].set(
        wins.reshape((-1,) + wins.shape[2:]))


def lm_prefill_paged(params: Dict[str, jax.Array], window: jax.Array,
                     kpool: jax.Array, vpool: jax.Array, table: jax.Array,
                     pos0: jax.Array, true_len: jax.Array, n_heads: int
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Prefill a right-padded SUFFIX window directly into pages.

    The prefix-hit admission path: positions 0..pos0-1 already hold
    valid K/V in shared pages (radix hit), so only the suffix is
    computed. window: (1, Wb) padded to a bucket with ``true_len`` real
    tokens; table: (B,) page ids. The causal row structure of the
    verify-window body gives padded-prompt masking for free: the
    returned logits row ``true_len - 1`` attends exactly columns <=
    pos0 + true_len - 1 (hit pages + the real suffix), never the padded
    rows' garbage — the same overwrite-before-visible contract as
    ``lm_prefill_masked``, relocated to pos0.

    Returns (logits (1, vocab), kpool', vpool', pos = pos0 + true_len).
    """
    with jax.default_matmul_precision(_PRECISION):
        p0 = jnp.asarray(pos0).reshape(()).astype(jnp.int32)
        tl = jnp.asarray(true_len).reshape(()).astype(jnp.int32)
        kv = _paged_view(kpool, table)
        vv = _paged_view(vpool, table)
        logits, kv, vv, _ = _lm_verify_window(
            params, window, kv, vv, p0.reshape(1), n_heads)
        last = jax.lax.dynamic_index_in_dim(logits[0], tl - 1, axis=0,
                                            keepdims=False)
        nt = paged_touch_span(window.shape[1], kpool.shape[2],
                              table.shape[0])
        kpool = _paged_update(kpool, kv, table, p0, nt)
        vpool = _paged_update(vpool, vv, table, p0, nt)
        return last[None], kpool, vpool, (p0 + tl).reshape(1)


def lm_verify_window_paged(params: Dict[str, jax.Array], tokens: jax.Array,
                           kpool: jax.Array, vpool: jax.Array,
                           tables: jax.Array, poss: jax.Array, n_heads: int
                           ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                      jax.Array]:
    """Verify windows for S slots against paged caches: gather each
    slot's view, run the same vmapped `_lm_verify_window` step as
    :func:`lm_verify_window_slots`, scatter back the touched pages.
    tokens: (S, W); tables: (S, B); poss: (S, 1). Returns (logits
    (S, W, vocab), kpool', vpool', poss+W). Slots past their view
    capacity B·ps NaN-poison their own row, same contract as the
    contiguous form."""
    with jax.default_matmul_precision(_PRECISION):
        kviews = paged_view_slots(kpool, tables)
        vviews = paged_view_slots(vpool, tables)
        step = lambda tok, kc, vc, pos: _lm_verify_window(  # noqa: E731
            params, tok[None], kc, vc, pos, n_heads)
        logits, kviews, vviews, poss2 = jax.vmap(step)(
            tokens, kviews, vviews, poss)
        nt = paged_touch_span(tokens.shape[1], kpool.shape[2],
                              tables.shape[1])
        p0s = poss[:, 0]
        kpool = paged_update_slots(kpool, kviews, tables, p0s, nt)
        vpool = paged_update_slots(vpool, vviews, tables, p0s, nt)
        return logits[:, 0], kpool, vpool, poss2


def lm_decode_step_paged(params: Dict[str, jax.Array], tokens: jax.Array,
                         kpool: jax.Array, vpool: jax.Array,
                         tables: jax.Array, poss: jax.Array, n_heads: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """One decode step for S slots against paged caches — the W=1 case
    of :func:`lm_verify_window_paged`, mirroring how the contiguous
    `lm_decode_step_slots` is the W=1 verify window. tokens: (S, 1, 1)."""
    return lm_verify_window_paged(
        params, tokens[:, :, 0], kpool, vpool, tables, poss, n_heads)


def prefill_flops(batch: int, seq: int, d_model: int, n_layers: int,
                  vocab: int, d_ff: int = 0) -> float:
    """Analytic forward FLOPs of one prefill (last-token unembed only).

    XLA's compiled ``cost_analysis()`` counts a ``lax.scan`` body ONCE
    regardless of trip count (verified empirically: identical "flops"
    for L=1/2/8 — tests/test_flops_accounting.py), so any layer-scanned
    model undercounts by ~L and MFU derived from it understates chip
    utilization by the same factor. Benchmarks use this closed form:
    per token per layer 2·D·3D (QKV) + 2·D² (proj) + 4·D·d_ff (MLP);
    causal attention QKᵀ+PV = 2·D·T·(T+1) per layer per sequence;
    plus the last-token unembed 2·D·V. LN/softmax/gather are omitted
    (sub-1% at these shapes), making the count slightly conservative.
    """
    d_ff = d_ff or 4 * d_model
    dense = 2 * d_model * 3 * d_model + 2 * d_model * d_model \
        + 4 * d_model * d_ff
    attn = 2 * d_model * seq * (seq + 1)
    return float(batch) * (n_layers * (dense * seq + attn)
                           + 2 * d_model * vocab)


def decode_flops(batch: int, pos0: int, n_steps: int, d_model: int,
                 n_layers: int, vocab: int, d_ff: int = 0) -> float:
    """Analytic FLOPs of ``n_steps`` KV-cache decode steps starting at
    cache position ``pos0`` (step i attends pos0+i+1 keys; each step
    pays the full per-token dense stack plus one unembed). Same
    motivation as :func:`prefill_flops` — the generate loop is a scan of
    a scan, which ``cost_analysis`` undercounts by ~L·n_steps."""
    d_ff = d_ff or 4 * d_model
    dense = 2 * d_model * 3 * d_model + 2 * d_model * d_model \
        + 4 * d_model * d_ff
    attn = 4 * d_model * (n_steps * (pos0 + 1)
                          + n_steps * (n_steps - 1) // 2)
    return float(batch) * (n_layers * (dense * n_steps + attn)
                           + n_steps * 2 * d_model * vocab)


def empty_cache(n_layers: int, batch: int, n_heads: int, max_len: int,
                head_dim: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(kcache, vcache, pos) zero state in the flat transport layout."""
    flat = (n_layers * batch * n_heads, max_len, head_dim)
    return (np.zeros(flat, np.float32), np.zeros(flat, np.float32),
            np.zeros((1,), np.int32))


def make_causal_lm(vocab: str = "256", dim: str = "64", heads: str = "4",
                   layers: str = "2", max_len: str = "128",
                   batch: str = "1", seed: str = "0",
                   **_: Any) -> ModelBundle:
    V, D, H, L = int(vocab), int(dim), int(heads), int(layers)
    M, B = int(max_len), int(batch)
    if D % H:
        raise ValueError(f"causal_lm: dim={D} not divisible by heads={H}")
    hd = D // H
    params = init_causal_lm(jax.random.PRNGKey(int(seed)), V, D, H, L, M)

    def apply(p, token, kcache, vcache, pos):
        return lm_decode_step(p, token.astype(jnp.int32), kcache, vcache,
                              pos, H)

    flat = L * B * H
    in_info = TensorsInfo.from_strings(
        f"1:{B},{hd}:{M}:{flat},{hd}:{M}:{flat},1",
        "int32,float32,float32,int32")
    out_info = TensorsInfo.from_strings(
        f"{V}:{B},{hd}:{M}:{flat},{hd}:{M}:{flat},1",
        "float32,float32,float32,int32")
    return ModelBundle(
        "causal_lm", apply, params=params,
        in_info=in_info, out_info=out_info,
        metadata={"vocab": V, "dim": D, "heads": H, "layers": L,
                  "max_len": M, "head_dim": hd, "batch": B})


register_model("causal_lm", make_causal_lm)
