"""LeNet-5 style MNIST CNN — the reference test-model-set parity entry.

Reference analog: tests/test_models/models/{mnist.pb, lenet_iter_9000.caffemodel}
(tiny classic CNNs the reference's tensorflow/caffe2 filter tests load).
TPU-native form: a flax module registered as ``zoo://lenet`` so the same
image-classification pipelines the reference runs over mnist.pb run here —
and export_model() produces the deployable artifact form.

Input: GRAY8 or float [1:W:H:1] (dims C:W:H innermost-first, default 28×28);
output: [num_classes:1] logits.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .zoo import ModelBundle, register_alias, register_model


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.tanh(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.tanh(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def make_lenet(size: str = "28", num_classes: str = "10", batch: str = "1",
               seed: str = "0", dtype: str = "float32",
               checkpoint: str = "", **_: Any) -> ModelBundle:
    hw, nc, b = int(size), int(num_classes), int(batch)
    model = LeNet5(num_classes=nc,
                   dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    variables = init_variables(model, int(seed),
                               jnp.zeros((b, hw, hw, 1), jnp.float32))
    if checkpoint:
        from ..utils import checkpoints

        variables = checkpoints.load_variables(checkpoint, variables)

    def apply(params, x):
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32) / 255.0
        if x.ndim == 3:  # (H, W, C) single frame
            x = x[None]
        return model.apply(params, x)

    return ModelBundle(
        "lenet", apply, params=variables,
        in_info=TensorsInfo.from_strings(f"1:{hw}:{hw}:{b}", "uint8"),
        out_info=TensorsInfo.from_strings(f"{nc}:{b}", "float32"))


register_model("lenet", make_lenet)
# alias matching the reference test-model name; resolves to the same
# canonical bundle (one memo entry, one compile)
register_alias("mnist", "lenet")
