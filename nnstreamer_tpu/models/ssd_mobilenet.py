"""SSD-MobileNet object detection — BASELINE config 2.

Native flax implementation of the SSD-MobileNet pipeline the reference runs
via tflite (tests/nnstreamer_decoder_boundingbox; decoder mode
mobilenet-ssd): MobileNet-v2 backbone + lightweight SSD heads emitting
``locations [N,anchors,4]`` and ``class logits [N,anchors,classes]`` — the
exact tensor pair tensordec-boundingbox.c decodes with a box-priors file.

``generate_anchors``/``write_box_priors`` produce the matching priors
(ycenter,xcenter,h,w rows) so the whole detection path is self-contained.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import TensorsInfo
from .mobilenet_v2 import ConvBNReLU, InvertedResidual, _make_divisible, preprocess_uint8
from .zoo import ModelBundle, register_model


class SSDMobileNetV2(nn.Module):
    """Backbone truncated at two strides + extra layers; one head per scale."""

    num_classes: int = 91
    width: float = 1.0
    anchors_per_cell: int = 6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        w = self.width
        feats: List[jax.Array] = []
        x = ConvBNReLU(_make_divisible(32 * w), stride=2, dtype=self.dtype)(x, train)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1)]
        for t, c, n, s in cfg:
            for i in range(n):
                x = InvertedResidual(_make_divisible(c * w), s if i == 0 else 1,
                                     t, dtype=self.dtype)(x, train)
        feats.append(x)  # stride 16
        for t, c, n, s in [(6, 160, 3, 2), (6, 320, 1, 1)]:
            for i in range(n):
                x = InvertedResidual(_make_divisible(c * w), s if i == 0 else 1,
                                     t, dtype=self.dtype)(x, train)
        feats.append(x)  # stride 32
        x = ConvBNReLU(_make_divisible(256 * w), kernel=1, dtype=self.dtype)(x, train)
        x = ConvBNReLU(_make_divisible(512 * w), stride=2, dtype=self.dtype)(x, train)
        feats.append(x)  # stride 64

        locs, logits = [], []
        k = self.anchors_per_cell
        for i, f in enumerate(feats):
            loc = nn.Conv(k * 4, (3, 3), padding="SAME", dtype=self.dtype,
                          name=f"loc_head_{i}")(f)
            cls = nn.Conv(k * self.num_classes, (3, 3), padding="SAME",
                          dtype=self.dtype, name=f"cls_head_{i}")(f)
            b = loc.shape[0]
            locs.append(loc.reshape(b, -1, 4))
            logits.append(cls.reshape(b, -1, self.num_classes))
        return (jnp.concatenate(locs, axis=1).astype(jnp.float32),
                jnp.concatenate(logits, axis=1).astype(jnp.float32))


def feature_grid_sizes(size: int) -> List[int]:
    return [math.ceil(size / 16), math.ceil(size / 32), math.ceil(size / 64)]


def generate_anchors(size: int, anchors_per_cell: int = 6,
                     min_scale: float = 0.2, max_scale: float = 0.95) -> np.ndarray:
    """Anchor grid matching the model's head layout → rows
    [ycenter, xcenter, h, w] (normalized), shape (4, total_anchors)."""
    grids = feature_grid_sizes(size)
    n_layers = len(grids)
    scales = [min_scale + (max_scale - min_scale) * i / max(n_layers - 1, 1)
              for i in range(n_layers)] + [1.0]
    ratios = [1.0, 2.0, 0.5, 3.0, 1.0 / 3.0]
    out = []
    for li, g in enumerate(grids):
        s = scales[li]
        s_next = math.sqrt(s * scales[li + 1])
        cell_anchors: List[Tuple[float, float]] = []
        for r in ratios[:anchors_per_cell - 1]:
            cell_anchors.append((s / math.sqrt(r), s * math.sqrt(r)))
        cell_anchors.append((s_next, s_next))
        for y, x in itertools.product(range(g), repeat=2):
            cy, cx = (y + 0.5) / g, (x + 0.5) / g
            for h, w in cell_anchors[:anchors_per_cell]:
                out.append((cy, cx, h, w))
    return np.asarray(out, np.float32).T  # (4, N)


def write_box_priors(path: str, size: int = 300,
                     anchors_per_cell: int = 6) -> int:
    """Write a tensordec-boundingbox-compatible priors file; returns anchor
    count."""
    pri = generate_anchors(size, anchors_per_cell)
    with open(path, "w", encoding="utf-8") as f:
        for row in pri:
            f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
    return pri.shape[1]


def make_ssd_mobilenet_v2(width: str = "1.0", size: str = "300",
                          num_classes: str = "91", seed: str = "0",
                          batch: str = "1", dtype: str = "bfloat16",
                          **_: Any) -> ModelBundle:
    w, hw, nc, b = float(width), int(size), int(num_classes), int(batch)
    model = SSDMobileNetV2(num_classes=nc, width=w,
                           dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    variables = init_variables(model, int(seed),
                               jnp.zeros((b, hw, hw, 3), jnp.float32))
    n_anchors = sum(g * g * 6 for g in feature_grid_sizes(hw))

    def apply(params, x):
        if x.dtype == jnp.uint8:
            x = preprocess_uint8(x)
        return model.apply(params, x, train=False)

    return ModelBundle(
        "ssd_mobilenet_v2", apply, params=variables,
        in_info=TensorsInfo.from_strings(f"3:{hw}:{hw}:{b}", "uint8"),
        out_info=TensorsInfo.from_strings(
            f"4:{n_anchors}:{b},{nc}:{n_anchors}:{b}", "float32,float32"),
        preprocess=preprocess_uint8,
        metadata={"anchors": n_anchors, "size": hw, "classes": nc})


register_model("ssd_mobilenet_v2", make_ssd_mobilenet_v2)
