"""Serialized model deployment: ``.jaxexport`` artifacts + checkpoint dirs.

Reference parity: the reference's central deployment story is loading an
opaque model *file* produced elsewhere (``framework=tflite
model=foo.tflite`` — tensor_filter_tensorflow_lite.cc:154; extension
auto-detect tensor_filter_common.c:1153-1260).  The TPU-native equivalent
is a **jax.export StableHLO artifact**: a params-closed, shape-specialized
XLA program serialized to one file.  A model exported in one process (or
on another host, with no access to the defining Python source) deploys in
a pipeline string as ``tensor_filter framework=xla-tpu model=foo.jaxexport``.

Two deployable forms:

* ``foo.jaxexport`` (also ``.stablehlo``/``.jax``) — ``export_model()``
  output: the serialized ``jax.export.Exported`` bytes.  Self-describing
  (input/output avals ride along); exported for both cpu and tpu by
  default so one artifact serves laptop validation and chip serving.
* checkpoint params (``.msgpack`` file or orbax directory) +
  ``custom="arch=zoo://..."`` — weights produced by a training job, glued
  to a zoo/py architecture at load time (utils/checkpoints).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from ..core.types import TensorInfo, TensorsInfo
from .zoo import ModelBundle

#: extensions treated as serialized jax.export artifacts
EXPORT_EXTS = (".jaxexport", ".stablehlo", ".jax")
#: extensions treated as parameter checkpoints needing custom="arch=..."
CKPT_EXTS = (".msgpack", ".ckpt", ".orbax")


def export_model(path: str, model: Any, example_args: Optional[Sequence] = None,
                 platforms: Tuple[str, ...] = ("cpu", "tpu")) -> None:
    """Serialize ``model`` (ModelBundle or params-closed callable) to
    ``path`` as a jax.export artifact runnable on ``platforms``.

    ``example_args`` fixes the input shapes/dtypes (XLA programs are
    shape-specialized); defaults to zeros of the bundle's ``in_info``.
    """
    import jax
    from jax import export as jexport

    if isinstance(model, ModelBundle):
        fn = model.fn()
        if example_args is None:
            if model.in_info is None:
                raise ValueError(
                    "export_model: bundle has no in_info; pass example_args")
            example_args = [np.zeros(i.shape, i.dtype.np_dtype)
                            for i in model.in_info]
    else:
        fn = model
        if example_args is None:
            raise ValueError("export_model: callables need example_args")
    avals = [jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
             for a in example_args]
    exported = jexport.export(jax.jit(fn), platforms=tuple(platforms))(*avals)
    with open(path, "wb") as f:
        f.write(exported.serialize())


def _info_from_avals(avals) -> TensorsInfo:
    infos = []
    for a in avals:
        shape = tuple(int(d) for d in a.shape) or (1,)
        infos.append(TensorInfo.from_shape(shape, np.dtype(a.dtype)))
    return TensorsInfo(tuple(infos))


def load_exported(path: str) -> ModelBundle:
    """``.jaxexport`` file → ModelBundle (I/O metadata from the artifact's
    avals; no defining Python source needed)."""
    from jax import export as jexport

    if not os.path.isfile(path):
        raise FileNotFoundError(path)
    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())

    def apply(*xs):
        out = exported.call(*xs)
        return out if isinstance(out, (tuple, list)) else (out,)

    name = os.path.splitext(os.path.basename(path))[0]
    return ModelBundle(
        name, apply,
        in_info=_info_from_avals(exported.in_avals),
        out_info=_info_from_avals(exported.out_avals),
        metadata={"deployed_from": path,
                  "platforms": tuple(exported.platforms)})


def load_checkpointed(path: str, arch: str, **arch_opts: Any) -> ModelBundle:
    """Checkpoint params (``.msgpack`` / orbax dir) + ``arch=`` spec →
    ModelBundle with the trained weights swapped in.

    ``arch`` is any model spec the zoo resolves (``zoo://...``) or a
    ``.py`` file exporting ``make_model`` — the same forms ``model=``
    accepts for in-source models.
    """
    from ..utils.checkpoints import load_variables
    from .zoo import get_model

    if arch.endswith(".py"):
        from ..filters.xla import _bundle_from_pyfile

        bundle = _bundle_from_pyfile(arch, arch_opts)
    else:
        bundle = get_model(arch, **arch_opts)
    if bundle.params is None:
        raise ValueError(
            f"arch {arch!r} has no parameters to restore into")
    params = load_variables(path, bundle.params)
    return ModelBundle(
        bundle.name, bundle.apply, params=params,
        in_info=bundle.in_info, out_info=bundle.out_info,
        preprocess=bundle.preprocess, postprocess=bundle.postprocess,
        metadata={**bundle.metadata, "deployed_from": path, "arch": arch})


def is_deployable_path(path: str) -> bool:
    """True for model= values the deploy loader owns (serialized artifact
    or checkpoint params)."""
    lower = path.lower()
    if lower.endswith(EXPORT_EXTS) or lower.endswith(CKPT_EXTS):
        return True
    return os.path.isdir(path)  # orbax checkpoint directory
