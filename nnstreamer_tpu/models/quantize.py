"""Weight-only int8 quantization for serving bundles.

The reference's flagship test pipelines serve *quantized* tflite models
(tests/test_models/models/mobilenet_v1_1.0_224_quant.tflite;
tensor_filter_tensorflow_lite.cc runs them via TFLite's int8 kernels).
The TPU-idiomatic equivalent is weight-only quantization: weights live in
HBM as int8 with per-output-channel scales (4× smaller, 4× less weight
bandwidth — the binding resource for memory-bound models) and are
dequantized to the compute dtype *inside* the XLA program, where the
dequant fuses into the consuming conv/matmul. Activations stay bf16/f32
on the MXU, which matches how the reference's decoders consume
dequantized outputs anyway (SURVEY §7 hard part d).

Usage — one flag at the filter:

    tensor_filter framework=xla-tpu model=zoo://mobilenet_v2 custom="quant=w8"

or programmatically ``quantize_bundle(bundle)``. Scales are
per-output-channel (last axis) absmax; rank<2 leaves (biases, norms) and
integer leaves stay float/unchanged — they are byte-trivial.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .zoo import ModelBundle

#: tag key marking a quantized leaf container
_QTAG = "__w8__"


def _quantize_leaf(w: Any) -> Any:
    arr = np.asarray(w)
    if arr.ndim < 2 or not np.issubdtype(arr.dtype, np.floating):
        return arr
    absmax = np.max(np.abs(arr), axis=tuple(range(arr.ndim - 1)))
    scale = (absmax / 127.0).astype(np.float32)
    safe = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(arr / safe), -127, 127).astype(np.int8)
    # original dtype recorded so dequant can restore it: a graph whose
    # activations are f32 (e.g. a tflite import) must get f32 weights
    # back or conv dtypes mismatch at trace. Carried as a ZERO-SIZE array
    # (a string leaf would break jit pytree flattening)
    return {_QTAG: q, "scale": scale,
            "orig": np.zeros((0,), arr.dtype)}


def _dequantize_leaf(leaf: Any, dtype) -> Any:
    if isinstance(leaf, dict) and _QTAG in leaf:
        if dtype is None:
            orig = leaf.get("orig")
            dt = orig.dtype if orig is not None else jnp.bfloat16
        else:
            dt = dtype
        return (leaf[_QTAG].astype(dt) *
                leaf["scale"].astype(dt))
    return leaf


def _is_quant(leaf: Any) -> bool:
    return isinstance(leaf, dict) and _QTAG in leaf


def quantize_params(params: Any) -> Any:
    """float leaves (rank ≥ 2) → {int8 weights, per-channel scales}."""
    return jax.tree_util.tree_map(_quantize_leaf, params)


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """dtype=None restores each leaf's recorded original dtype."""
    return jax.tree_util.tree_map(
        lambda leaf: _dequantize_leaf(leaf, dtype), params,
        is_leaf=_is_quant)


def params_nbytes(params: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += np.asarray(leaf).nbytes
    return total


def quantize_bundle(bundle: ModelBundle,
                    compute_dtype=None) -> ModelBundle:
    """Serving bundle with int8-quantized weights; the dequant runs inside
    the jitted program (fused into the consuming ops by XLA).

    ``compute_dtype=None`` (default) dequantizes each weight back to its
    ORIGINAL dtype, so any graph serves unchanged (bf16 zoo bundles stay
    bf16, f32 tflite imports stay f32); pass an explicit dtype to force
    one."""
    if bundle.params is None:
        raise ValueError("quantize_bundle: bundle has no params "
                         "(in-process callable models cannot be quantized)")
    qparams = quantize_params(bundle.params)
    base_apply = bundle.apply

    def apply(p, *xs):
        return base_apply(dequantize_params(p, compute_dtype), *xs)

    return replace(
        bundle,
        name=f"{bundle.name}:w8",
        apply=apply,
        params=qparams,
        metadata={**bundle.metadata, "quantized": "w8",
                  "params_nbytes": params_nbytes(qparams),
                  "params_nbytes_f32": params_nbytes(bundle.params),
                  # a fresh jit cache: the float bundle's compiled
                  # programs must not be reused for the tagged pytree
                  "_jit_cache": {}})


def quantize_bundle_w8a8(bundle: ModelBundle) -> ModelBundle:
    """Serving bundle on the MXU's int8 double-rate path (w8a8): int8
    weights AND dynamically-quantized int8 activations, contracted in
    exact int32 (ops/int8.py — 2x the bf16 peak on v5e).

    Unlike weight-only ``quantize_bundle`` this needs the model's GEMM
    sites instrumented (ops/int8.matmul_any), which the causal-LM family
    is — so it applies to param trees with the LM's GEMM stacks. The
    apply is UNCHANGED: matmul_any dispatches on the quantized leaves.
    """
    p = bundle.params
    if p is None or not isinstance(p, dict) or \
            not all(k in p for k in ("wqkv", "wo", "w1", "w2")):
        raise ValueError(
            "quant=w8a8 serves models whose GEMMs run through "
            "ops/int8.matmul_any (the causal-LM family: zoo://causal_lm "
            "param trees); use quant=w8 (weight-only) for arbitrary "
            "bundles")
    from .causal_lm import quantize_lm_params

    qparams = quantize_lm_params(p)
    return replace(
        bundle,
        name=f"{bundle.name}:w8a8",
        params=qparams,
        metadata={**bundle.metadata, "quantized": "w8a8",
                  "params_nbytes": params_nbytes(qparams),
                  "params_nbytes_f32": params_nbytes(bundle.params),
                  "_jit_cache": {}})
