"""Streaming transformer encoder — long-sequence workloads over the mesh.

New capability beyond the reference (SURVEY §5 lists sequence parallelism
as absent there): a transformer filter for token/feature streams (e.g.
tensor_aggregator windows of per-frame embeddings) whose attention can run
**sequence-parallel** across a device mesh via parallel.ring — ring
attention (ppermute ring over ICI) or Ulysses all-to-all — so context
length scales with the number of chips.

Zoo entry: ``zoo://stream_transformer?layers=2&dim=128&heads=8&seq=256``
(+``sp=ring|a2a`` with a mesh for sharded runs via ``make_sp_apply``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.types import TensorsInfo
from .zoo import ModelBundle, register_model


class Block(nn.Module):
    """Transformer block. The MLP half is a vmethod (``_mlp_residual``) so
    variants (e.g. the MoE block in models/moe_transformer.py) share the
    attention half instead of copying it."""

    dim: int
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None  # (q,k,v)->o, [B,H,L,hd]

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        B, L, D = h.shape
        hd = D // self.heads
        qkv = nn.Dense(3 * D, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        reshape = lambda t: t.reshape(B, L, self.heads, hd).transpose(0, 2, 1, 3)
        q, k, v = reshape(q), reshape(k), reshape(v)
        if self.attention_fn is not None:
            o = self.attention_fn(q, k, v)
        else:
            from ..parallel.ring import reference_attention

            o = reference_attention(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, L, D).astype(self.dtype)
        x = x + nn.Dense(D, dtype=self.dtype)(o)
        return self._mlp_residual(x)

    def _mlp_residual(self, x):
        D = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(D * self.mlp_ratio, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(D, dtype=self.dtype)(h)


class StreamTransformer(nn.Module):
    layers: int = 2
    dim: int = 128
    heads: int = 8
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        if x.shape[-1] != self.dim:
            x = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.layers):
            x = Block(self.dim, self.heads, dtype=self.dtype,
                      attention_fn=self.attention_fn, name=f"block_{i}")(x)
        return nn.LayerNorm(dtype=self.dtype)(x).astype(jnp.float32)


def make_stream_transformer(layers: str = "2", dim: str = "128",
                            heads: str = "8", seq: str = "256",
                            in_dim: str = "", batch: str = "1",
                            seed: str = "0", dtype: str = "bfloat16",
                            **_: Any) -> ModelBundle:
    L, D, B = int(seq), int(dim), int(batch)
    d_in = int(in_dim) if in_dim else D
    model = StreamTransformer(
        layers=int(layers), dim=D, heads=int(heads),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    from .zoo import init_variables

    params = init_variables(model, int(seed),
                            jnp.zeros((B, L, d_in), jnp.float32))
    return ModelBundle(
        "stream_transformer", lambda p, x: model.apply(p, x), params=params,
        in_info=TensorsInfo.from_strings(f"{d_in}:{L}:{B}", "float32"),
        out_info=TensorsInfo.from_strings(f"{D}:{L}:{B}", "float32"),
        metadata={"layers": int(layers), "dim": D, "heads": int(heads),
                  "seq": L})


def make_sp_apply(bundle: ModelBundle, mesh, mode: str = "ring",
                  axis_name: str = "sp", causal: bool = False):
    """Rebuild the bundle's apply with sequence-parallel attention over
    ``mesh``: returns (apply_fn, params). Inputs/outputs are globally-shaped;
    shard the L axis with PartitionSpec(None, axis_name, None)."""
    from ..parallel.ring import sp_attention_fn

    meta = bundle.metadata
    attn = sp_attention_fn(mode, mesh, axis_name, causal=causal)
    model = StreamTransformer(layers=meta["layers"], dim=meta["dim"],
                              heads=meta["heads"], dtype=jnp.float32,
                              attention_fn=attn)
    return (lambda p, x: model.apply(p, x)), bundle.params


register_model("stream_transformer", make_stream_transformer)
